"""Continuous-batching inference engine (JetStream-style decode, SURVEY §7.1).

The round-1 engine decoded one request at a time (batch=1, LoRA merged at
load). This engine runs a SINGLE jitted decode program over S cache slots and
admits new requests into free slots between decode chunks — the serving tier
the reference buys from Ray Serve (reference pkg/util/generate/
generate.go:160-329 deploys LlamaDeployment replicas), rebuilt TPU-first:

- per-slot KV cache cursors (models/llama.py ``init_cache(per_slot=True)``):
  rows sit at different depths inside one program; sentinel rope positions
  mask free/garbage slots, so no per-slot programs and no re-batching pauses;
- PAGED KV cache (``kv_block_size > 0``, ops/paged_attention.py): the cache
  is a pool of fixed-size blocks + per-slot block tables instead of dense
  ``slots × max_seq_len`` rows. Admission reserves ``ceil((prompt +
  max_new) / block_size)`` blocks from a free list — a short chat no longer
  strands a full-width row of HBM, so a smaller pool (``kv_blocks``) carries
  the same traffic, or the same pool carries more slots;
- CHUNKED PREFILL (paged mode): a cold prompt prefills directly into its
  slot's blocks in ``prefill_chunk``-token programs, interleaved with decode
  — the scheduler spends at most ``prefill_token_budget`` prefill tokens
  between decode chunks, so one long prompt can no longer stall every
  in-flight decode for its whole prefill (Sarathi-style stall-free
  scheduling; bounds TTFT and TPOT under mixed long/short load);
- decode runs in CHUNKS of K tokens per program (``lax.scan`` over the
  single-token step): K amortizes dispatch latency (fatal over a tunneled
  accelerator at K=1) while keeping admission latency bounded at K tokens;
- UNMERGED multi-adapter LoRA: adapters are stacked ([L, E, d, r]) and each
  slot indexes its own adapter inside the matmul (models/llama.py _proj
  lora_idx) — one base model serves many tuned jobs concurrently;
- streaming: each emitted token lands on the request's queue as soon as its
  chunk completes (SSE transport in serving/server.py).
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.obs.metrics import (
    Registry,
    adapter_load_histogram,
    serving_latency_histograms,
)
from datatunerx_tpu.obs.trace import TraceStore, build_request_span
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.models.lora import LORA_TARGETS, lora_scaling
from datatunerx_tpu.ops.paged_attention import (
    POS_SENTINEL,
    BlockAllocator,
    blocks_for_depth,
    init_paged_cache,
    paged_copy_block,
    paged_extract_row,
    paged_insert_row,
)
from datatunerx_tpu.ops.pallas_sampling import (
    default_impl as sampling_default_impl,
    sample_rows,
)
from datatunerx_tpu.serving.engine import _sample_jit
from datatunerx_tpu.utils.decoding import DECODE_BUCKET
from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer

MAX_STOP = 8  # static per-slot stop-token capacity

# global arrival order: preemption fairness (never preempt the oldest,
# resume strictly before admitting anything younger) needs a total order
# across waiting, parked, and slot-holding requests; itertools.count is
# C-level atomic, so concurrent submit() threads need no extra lock
_REQ_SEQ = itertools.count()


class _RetryLater(Exception):
    """A migration command that can't complete THIS tick but may next one
    (adapter mid-load, no free slot, KV blocks exhausted) — the scheduler
    re-queues it until its deadline."""


class _PrefixCache:
    """Host-side LRU of prefilled single-row KV caches keyed by
    (prompt tokens, adapter). An exact hit skips prefill entirely; the longest
    strict-prefix hit turns prefill into a (shorter) suffix extension — the
    prefix-reuse tier of paged serving stacks (vLLM/JetStream), host-managed
    here because rows are full-width and slots are few.

    Lookup structure is a per-adapter token TRIE: ``longest_prefix`` walks at
    most ``len(tokens)`` nodes, so admission cost is O(prompt_len) instead of
    the round-2 O(entries × prompt_len) linear scan over all stored keys.
    The OrderedDict keeps only LRU recency + the entry payloads; the trie
    mirrors its key set (terminal nodes point back at the exact key).

    Entries: {"cache": row_cache, "logits": last-token logits,
    "cursor": cache write depth}. Stored row caches are immutable JAX
    arrays — inserting a row into a slot copies, and extension builds a new
    functional cache, so shared prefixes are safe.

    COW mode (kv_overcommit engines) stores BLOCK entries instead:
    {"blocks": [ids], "full": n, "rem": r, "cursor", "logits"} — refcounted
    physical blocks a hit maps straight into the new slot's table, no dense
    row anywhere. ``on_evict`` receives every entry leaving the cache
    (capacity eviction, same-key replacement, drop_adapter) so the engine
    can return block entries' refs to the allocator.
    """

    def __init__(self, capacity: int, on_evict=None):
        from collections import OrderedDict

        self.capacity = capacity
        self._on_evict = on_evict
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        # adapter -> trie root; node = [children {tok: node}, terminal key]
        self._roots: Dict[int, list] = {}
        self.evictions = 0
        # the scheduler thread is the lookup/insert path, but the dynamic
        # adapter plane invalidates from admin HTTP threads (drop_adapter
        # on unload/rebind) — the lock keeps the dict+trie consistent;
        # host-side dict work, negligible next to any device call
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._d)

    def get(self, key):
        with self._lock:
            ent = self._d.get(key)
            if ent is not None:
                self._d.move_to_end(key)
            return ent

    def longest_prefix(self, tokens: tuple, adapter: int):
        """Longest stored strict prefix of ``tokens`` for this adapter —
        one trie descent, deepest terminal wins."""
        with self._lock:
            node = self._roots.get(adapter)
            if node is None:
                return None, None
            best_key = None
            for i in range(len(tokens) - 1):  # strict: depth < len(tokens)
                node = node[0].get(tokens[i])
                if node is None:
                    break
                if node[1] is not None:
                    best_key = node[1]
            if best_key is None:
                return None, None
            self._d.move_to_end(best_key)
            return best_key, self._d[best_key]

    def put(self, key, ent):
        dropped = []
        with self._lock:
            is_new = key not in self._d
            if not is_new:
                # same-key replacement: the old entry's resources (COW
                # block refs) must be released like any other eviction
                dropped.append(self._d[key])
            self._d[key] = ent
            self._d.move_to_end(key)
            if is_new:
                ptoks, adapter = key
                node = self._roots.setdefault(adapter, [{}, None])
                for t in ptoks:
                    node = node[0].setdefault(t, [{}, None])
                node[1] = key
            while len(self._d) > self.capacity:
                old_key, old_ent = self._d.popitem(last=False)
                self._trie_remove(old_key)
                self.evictions += 1
                dropped.append(old_ent)
        # outside the lock: the callback frees allocator blocks (its own
        # lock) and must never nest under this one
        self._notify_evicted(dropped)

    def _notify_evicted(self, entries):
        if self._on_evict is None:
            return
        for ent in entries:
            self._on_evict(ent)

    def snapshot_entries(self):
        """MRU-first (key, entry) pairs WITHOUT touching recency — the
        fleet prefix tier's publish scan. The list is a point-in-time
        copy; entries may be evicted while the caller iterates (COW block
        entries are only freed via on_evict, so a concurrently-evicted
        entry's blocks may already be recycled — callers on the scheduler
        thread are safe, eviction happens there or under drop_adapter
        which the admin surface serializes)."""
        with self._lock:
            return list(reversed(list(self._d.items())))

    def pop_lru_block_entry(self):
        """Evict (and return) the least-recently-used BLOCK entry — the
        overcommit scheduler's first reclamation tier when growth finds
        the pool empty: cached prefixes are a performance tier, live
        sessions are the product. None when no block entries remain.
        The caller owns the entry's block refs (on_evict is NOT called)."""
        with self._lock:
            for key, ent in self._d.items():
                if ent.get("blocks"):
                    del self._d[key]
                    self._trie_remove(key)
                    self.evictions += 1
                    return ent
        return None

    def drop_adapter(self, adapter):
        """Invalidate every entry cached under one adapter identity —
        required when an adapter NAME is rebound to different weights
        (unload / re-register): cached KV rows were computed with the old
        weights and would silently poison the new binding. Called from
        admin threads; the lock covers the scheduler's concurrent use."""
        dropped = []
        with self._lock:
            for key in [k for k in self._d if k[1] == adapter]:
                dropped.append(self._d.pop(key))
                self._trie_remove(key)
        self._notify_evicted(dropped)

    def _trie_remove(self, key):
        ptoks, adapter = key
        root = self._roots.get(adapter)
        if root is None:
            return
        path, node = [root], root
        for t in ptoks:
            node = node[0].get(t)
            if node is None:
                return
            path.append(node)
        node[1] = None
        # prune now-useless nodes bottom-up so the trie never outgrows
        # capacity × prompt_len
        for i in range(len(path) - 1, 0, -1):
            n = path[i]
            if n[0] or n[1] is not None:
                break
            del path[i - 1][0][ptoks[i - 1]]
        if not root[0] and root[1] is None:
            del self._roots[adapter]


class Request:
    def __init__(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 temperature: float, top_p: float, seed: int,
                 stop_ids: Sequence[int], adapter: int,
                 adapter_name: str = "", trace_id: str = "",
                 tenant: str = "", tenant_tier: str = "standard"):
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_p = top_p
        self.seed = seed
        self.stop_ids = list(stop_ids)[:MAX_STOP]
        # arrival order across every parked population (waiting queue,
        # preemption parking, slots) — the preemption policy's fairness
        # and never-preempt-the-oldest invariants compare these
        self.seq = next(_REQ_SEQ)
        # device pool/stack index; in dynamic mode -1 until admission
        # resolves (and pins) the NAME to a pool slot via the registry
        self.adapter = adapter
        self.adapter_name = adapter_name
        # tenancy plane: resolved at submit from the engine's directory
        # (header name first, adapter mapping second). "" = anonymous —
        # scheduled exactly like a pre-tenancy request. tenant_tier feeds
        # the overcommit preemption order; see _reclaim_for.
        self.tenant = tenant
        self.tenant_tier = tenant_tier
        # residency at FIRST admission attempt (None until then) — the
        # trace's loaded flag must reflect whether this request paid the
        # load, not the state after its own load completed
        self.adapter_was_resident: Optional[bool] = None
        # hit/miss stats latch: a readmission retry (pin released on
        # KV-block exhaustion) must not re-count this request's lookup
        self.adapter_stats_counted = False
        self.tokens: List[int] = []
        self.stream: "queue.Queue[Optional[int]]" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None
        # --- observability: the request's own span timeline. Stamps are
        # plain attribute writes from the scheduler thread (no locks, no
        # device reads) so recording never perturbs the decode loop.
        self.trace_id = trace_id
        self.t_submit = time.perf_counter()
        self.wall_submit_ms = time.time() * 1e3
        self.timeline: List[tuple] = []  # (perf stamp, event, detail dict)
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None

    def mark(self, event: str, **detail):
        self.timeline.append((time.perf_counter(), event, detail))

    def push(self, token: int):
        # token arrival stamps: taken right after the decode chunk's designed
        # host sync, so TTFT/TPOT derived from them are true wall numbers
        now = time.perf_counter()
        if self.first_token_ts is None:
            self.first_token_ts = now
        self.last_token_ts = now
        self.tokens.append(token)
        self.stream.put(token)

    def finish(self, error: Optional[str] = None):
        self.error = error
        self.stream.put(None)
        self.done.set()


def _pad_row(row: Dict, width: int) -> Dict:
    """Sentinel-pad a cursor-trimmed dense row cache back to ``width``.
    Stored prefix rows are trimmed to their live cursor (no full
    ``max_seq_len`` gather per insert), but the extension program keeps ONE
    compiled geometry — full width — so padding happens here, once per
    extension, instead of a compile per stored prefix length."""
    W = row["k"].shape[2]
    if W >= width:
        return row
    out = dict(row)
    pad5 = [(0, 0), (0, 0), (0, width - W), (0, 0), (0, 0)]
    out["k"] = jnp.pad(row["k"], pad5)
    out["v"] = jnp.pad(row["v"], pad5)
    if "k_scale" in row:
        out["k_scale"] = jnp.pad(row["k_scale"], pad5[:-1])
        out["v_scale"] = jnp.pad(row["v_scale"], pad5[:-1])
    out["pos"] = jnp.pad(row["pos"], [(0, 0), (0, width - W)],
                         constant_values=POS_SENTINEL)
    return out


def load_checkpoint_state(checkpoint_path: str) -> dict:
    """Load an Orbax TrainState checkpoint dir (…/checkpoints[/<step>]) and
    return its raw state dict ({"lora": …} and/or {"params": …}), plus the
    recorded manifest lora scaling under "_scaling" when available."""
    import os

    import orbax.checkpoint as ocp

    from datatunerx_tpu.serving.engine import InferenceEngine

    root = checkpoint_path.rstrip("/")
    step: Optional[int] = None
    if os.path.basename(root).isdigit():
        step = int(os.path.basename(root))
        root = os.path.dirname(root)
    mngr = ocp.CheckpointManager(root)
    step = step if step is not None else mngr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {checkpoint_path}")
    from datatunerx_tpu.training.checkpoint import restore_raw_state

    restored = restore_raw_state(mngr, step)
    mngr.close()
    state = restored if isinstance(restored, dict) else dict(restored)
    state["_scaling"] = InferenceEngine._manifest_lora_scaling(root)
    return state


# Bounded LRU: each entry pins the donor engine's closure (its jitted bound
# methods) + the compiled executables, so an unbounded dict would leak across
# a long-lived process cycling many distinct configs. 8 covers any realistic
# set of concurrently-live serving configs; evicted entries free their
# executables once the owning engines are gone.
_PROGRAM_MEMO: "collections.OrderedDict" = collections.OrderedDict()
_PROGRAM_MEMO_MAX = 8


def _program_memo_key(cfg, max_seq_len: int, kv_quant,
                      epilogue: str = "off"):
    """Hashable identity of the engine's traced programs, or None when it
    can't be established (exotic values → compile fresh). The dataclass repr
    covers every model-config field deterministically. Adapters are NOT part
    of the key: LoRA weights (a stacked tree or the dynamic pool) enter the
    programs as ARGUMENTS, so jax's own executable cache keys on their
    shapes — any adapter set with the same geometry shares one compiled
    program, and loading/unloading a pool adapter recompiles nothing.
    ``epilogue`` (the RESOLVED sampling-epilogue impl: "off" | "kernel" |
    "xla") changes what the decode program traces, so it keys too."""
    try:
        return (repr(cfg), int(max_seq_len), kv_quant, epilogue)
    except Exception:  # noqa: BLE001 — memoization is best-effort
        return None


class _Programs:
    """The engine's jitted device programs, factored OFF the engine so the
    process-wide memo pins only what tracing actually reads — the model
    config and two cache-geometry scalars — never a donor engine's full
    params, live KV pool, or adapter weights. Everything else (params,
    cache, the LoRA stack/pool, per-slot decode state) arrives as an
    argument, which is what makes the programs shareable across engines in
    the first place.

    ``lora`` is ``None`` (base-only engine) or ``(tree, scales)`` with
    stacked ``[L, E, …]`` leaves; None-vs-tuple is pytree STRUCTURE, so jax
    compiles the two cases separately and, within the adapter case, per
    leaf shape — mutating pool contents in place (same shapes) hits the
    same executable."""

    def __init__(self, cfg, max_seq_len: int, kv_quant,
                 epilogue: str = "off"):
        self.cfg = cfg
        self.max_seq_len = max_seq_len
        self.kv_quant = kv_quant
        # resolved fused-sampling-epilogue impl ("off" | "kernel" | "xla");
        # "off" keeps the legacy argsort sampler — byte-identical programs
        self.epilogue = epilogue
        self.prefill = jax.jit(self._prefill_impl,
                               static_argnames=("prompt_len",))
        self.extend = jax.jit(self._extend_impl,
                              static_argnames=("suffix_len",))
        self.insert = jax.jit(self._insert_impl)
        self.insert_paged = jax.jit(self._insert_paged_impl)
        self.activate = jax.jit(self._activate_impl)
        self.prefill_chunk = jax.jit(self._prefill_chunk_impl,
                                     static_argnames=("chunk_len",))
        self.extract = jax.jit(paged_extract_row,
                               static_argnames=("width",))
        self.copy_block = jax.jit(paged_copy_block)
        self.decode = jax.jit(self._decode_impl,
                              static_argnames=("K", "mode"))

    def _prefill_impl(self, params, lora, tokens, mask, positions,
                      adapter_idx, *, prompt_len: int):
        cache = init_cache(self.cfg, 1, self.max_seq_len, dtype=jnp.bfloat16,
                           quantize=self.kv_quant)
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=cache, lora=lora,
            lora_adapter_idx=(adapter_idx[None]
                              if lora is not None else None),
            compute_dtype=jnp.bfloat16,
        )
        return logits[0, prompt_len - 1], cache

    def _extend_impl(self, params, lora, row_cache, tokens, mask, positions,
                     adapter_idx, *, suffix_len: int):
        """Append a (left-pad-bucketed) prompt suffix onto a cached prefix
        row: pads get sentinel rope positions so only the real tokens exist
        for attention, exactly as in full prefill."""
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=row_cache, lora=lora,
            lora_adapter_idx=(adapter_idx[None]
                              if lora is not None else None),
            compute_dtype=jnp.bfloat16,
        )
        return logits[0, suffix_len - 1], cache

    def _insert_impl(self, cache, logits_all, pos, remaining, active, temps,
                     top_ps, stops, adapter_idx, rng,
                     slot, row_cache, row_logits, plen, n_prompt, max_new,
                     temp, top_p, stop_row, adapter, seed):
        cache = dict(cache)
        cache["k"] = jax.lax.dynamic_update_slice(
            cache["k"], row_cache["k"], (0, slot, 0, 0, 0))
        cache["v"] = jax.lax.dynamic_update_slice(
            cache["v"], row_cache["v"], (0, slot, 0, 0, 0))
        if "k_scale" in cache:
            cache["k_scale"] = jax.lax.dynamic_update_slice(
                cache["k_scale"], row_cache["k_scale"], (0, slot, 0, 0))
            cache["v_scale"] = jax.lax.dynamic_update_slice(
                cache["v_scale"], row_cache["v_scale"], (0, slot, 0, 0))
        cache["pos"] = jax.lax.dynamic_update_slice(
            cache["pos"], row_cache["pos"], (slot, 0))
        cache["len"] = cache["len"].at[slot].set(plen)
        return (
            cache,
            logits_all.at[slot].set(row_logits),
            pos.at[slot].set(n_prompt),
            remaining.at[slot].set(max_new),
            active.at[slot].set(True),
            temps.at[slot].set(temp),
            top_ps.at[slot].set(top_p),
            stops.at[slot].set(stop_row),
            adapter_idx.at[slot].set(adapter),
            rng.at[slot].set(jax.random.PRNGKey(seed)),
        )

    def _insert_paged_impl(self, cache, logits_all, pos, remaining, active,
                           temps, top_ps, stops, adapter_idx, rng,
                           slot, table_row, row_cache, row_logits, cursor,
                           n_prompt, max_new, temp, top_p, stop_row, adapter,
                           seed):
        """Paged twin of ``_insert_impl``: scatter a dense prefill/prefix row
        into the slot's allocated blocks (installing its block table) and arm
        the slot's decode state."""
        cache = paged_insert_row(cache, slot, table_row, row_cache)
        cache["len"] = jax.lax.dynamic_update_slice(
            cache["len"], cursor[None], (slot,))
        return (
            cache,
            logits_all.at[slot].set(row_logits),
            pos.at[slot].set(n_prompt),
            remaining.at[slot].set(max_new),
            active.at[slot].set(True),
            temps.at[slot].set(temp),
            top_ps.at[slot].set(top_p),
            stops.at[slot].set(stop_row),
            adapter_idx.at[slot].set(adapter),
            rng.at[slot].set(jax.random.PRNGKey(seed)),
        )

    def _activate_impl(self, logits_all, pos, remaining, active, temps,
                       top_ps, stops, adapter_idx, rng,
                       slot, row_logits, n_prompt, max_new, temp, top_p,
                       stop_row, adapter, seed):
        """Arm a slot whose prompt was already chunk-prefilled in place (its
        KV lives in the slot's blocks; only the decode state needs setting)."""
        return (
            logits_all.at[slot].set(row_logits),
            pos.at[slot].set(n_prompt),
            remaining.at[slot].set(max_new),
            active.at[slot].set(True),
            temps.at[slot].set(temp),
            top_ps.at[slot].set(top_p),
            stops.at[slot].set(stop_row),
            adapter_idx.at[slot].set(adapter),
            rng.at[slot].set(jax.random.PRNGKey(seed)),
        )

    def _prefill_chunk_impl(self, params, lora, cache, slot, tokens, mask,
                            positions, adapter_idx, *, chunk_len: int):
        """One ``chunk_len``-token prefill program writing straight into one
        slot's blocks of the SHARED pool — the chunk-bounded generalisation of
        ``_prefill_impl``/``_extend_impl``. Returns the chunk's last-token
        logits (only the final chunk's are consumed) and the updated cache."""
        nbps = cache["block_tables"].shape[1]
        view = dict(cache)
        view["len"] = jax.lax.dynamic_slice(cache["len"], (slot,), (1,))
        view["block_tables"] = jax.lax.dynamic_slice(
            cache["block_tables"], (slot, 0), (1, nbps))
        logits, new = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=view, lora=lora,
            lora_adapter_idx=(adapter_idx[None]
                              if lora is not None else None),
            compute_dtype=jnp.bfloat16,
        )
        out = dict(cache)
        for key in ("k", "v", "k_scale", "v_scale"):
            if key in out:
                out[key] = new[key]
        out["pos"] = new["pos"]
        out["len"] = jax.lax.dynamic_update_slice(
            cache["len"], new["len"], (slot,))
        return logits[0, chunk_len - 1], out

    def _decode_impl(self, params, lora, cache, logits, pos, remaining,
                     active, rng, temps, top_ps, stops, adapter_idx, *,
                     K: int, mode: str = "off"):
        """``mode`` is the engine's static per-batch sampling mode when the
        fused epilogue is on ("greedy" | "simple" | "topp"), or the
        ``"off"`` sentinel — ONE compiled variant running the legacy
        argsort sampler, byte-identical to the pre-epilogue program."""
        def step(carry, _):
            logits, cache, pos, remaining, active, rng = carry
            if mode == "off" or self.epilogue == "off":
                split = jax.vmap(jax.random.split)(rng)
                rng, sub = split[:, 0], split[:, 1]
                nxt = jax.vmap(_sample_jit)(logits, temps, top_ps, sub)
            else:
                nxt, rng = sample_rows(logits, temps, top_ps, rng,
                                       mode=mode, impl=self.epilogue)
            is_stop = jnp.any(nxt[:, None] == stops, axis=1)
            emit = active & ~is_stop & (remaining > 0)
            emitted = jnp.where(emit, nxt, -1)
            new_active = emit & (remaining > 1)
            remaining = remaining - emit.astype(jnp.int32)

            prev_len = cache["len"]
            tok = jnp.where(emit, nxt, 0)[:, None]
            logits2, cache = forward(
                params, tok, self.cfg, positions=pos[:, None],
                attention_mask=emit[:, None].astype(jnp.int32), cache=cache,
                lora=lora,
                lora_adapter_idx=(adapter_idx
                                  if lora is not None else None),
                compute_dtype=jnp.bfloat16,
            )
            # forward advances every cursor; only emitting slots really moved
            cache = dict(cache)
            cache["len"] = prev_len + emit.astype(jnp.int32)
            pos = pos + emit.astype(jnp.int32)
            return (logits2[:, -1], cache, pos, remaining, new_active, rng), emitted

        (logits, cache, pos, remaining, active, rng), emitted = jax.lax.scan(
            step, (logits, cache, pos, remaining, active, rng), None, length=K
        )
        return emitted, logits, cache, pos, remaining, active, rng


class BatchedEngine:
    def __init__(
        self,
        model_path: str,
        checkpoint_path: Optional[str] = None,
        adapters: Optional[Dict[str, str]] = None,  # name -> checkpoint path
        adapter_pool: int = 0,  # >0: dynamic pooled-adapter mode (P slots)
        adapter_rank_max: int = 8,  # pool rank ceiling (ranks < are padded)
        adapter_targets: Optional[Sequence[str]] = None,  # pool target set
        template: str = "llama2",
        max_seq_len: int = 1024,
        slots: int = 4,
        decode_chunk: int = 8,
        dtype=jnp.bfloat16,
        kv_quant: Optional[str] = None,  # "int8" halves cache HBM
        prefix_cache: int = 0,  # LRU entries of reusable prefilled prefixes
        kv_block_size: int = 0,  # >0: paged block-pool cache (elastic HBM)
        kv_blocks: Optional[int] = None,  # pool size; default = dense parity
        kv_overcommit: str = "off",  # on: lazy block growth + COW + preempt
        paged_kernel: str = "auto",  # Pallas in-place decode: auto|on|off
        spec_draft: Optional[str] = None,  # draft model: path|preset:|take:N
        spec_k: int = 4,  # proposals per verify step (adaptive ceiling)
        spec_mode: str = "auto",  # auto (adaptive) | on (pinned) | off
        spec_tree: Optional[str] = None,  # "WxD" tree drafts (None = chain)
        spec_tree_learned: bool = True,  # learned per-depth widths + early exit
        sampling_epilogue: str = "auto",  # fused on-chip sampling: auto|on|off
        prefill_chunk: int = 256,  # chunked-prefill program length (paged)
        prefill_token_budget: int = 0,  # prefill tokens per tick (0 = all)
        registry: Optional[Registry] = None,  # shared /metrics registry
        tracing: bool = True,  # per-request span timelines + trace ring
        trace_ring: int = 256,  # completed traces kept for /debug/trace
        trace_log_path: Optional[str] = None,  # optional JSONL span log
        prefix_keep_warm: bool = False,  # publish prompt blocks on preempt
        tenants=None,  # TenantDirectory / dict / path / inline JSON
        host_adapter_cache_mb: float = 0.0,  # host-RAM adapter tier budget
    ):
        # serving is single-program: clear any mesh a Trainer left in the
        # process-global flash context before the engine's jits first trace
        from datatunerx_tpu.ops.flash_attention import set_flash_context

        set_flash_context(None)
        self.cfg, self.params, self.tokenizer = load_model_and_tokenizer(
            model_path, dtype=dtype
        )
        self.template: Template = get_template(template, self.tokenizer)
        self.max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        self.slots = slots
        self.chunk = max(1, decode_chunk)

        # ---- adapters: checkpoint_path becomes adapter "default" (unmerged);
        # full-param checkpoints swap the base instead
        named: Dict[str, str] = dict(adapters or {})
        if checkpoint_path:
            state = load_checkpoint_state(checkpoint_path)
            if state.get("lora"):
                named.setdefault("default", checkpoint_path)
            elif state.get("params"):
                self.params = jax.device_put(state["params"])
        self._static_adapter_ids: Dict[str, int] = {"": 0}  # 0 = base
        self.lora_stack: Optional[tuple] = None
        # multi-tenant QoS plane (datatunerx_tpu/tenancy/): tenant → tier /
        # adapter set / share / KV quota. None (the default) keeps every
        # path below — eviction order, preemption order, /metrics bytes —
        # identical to a tenancy-less build (the PR 15/16 gating pattern).
        from datatunerx_tpu.tenancy import load_tenants

        self.tenants = load_tenants(tenants)
        self.host_adapter_cache_mb = float(host_adapter_cache_mb or 0.0)
        # per-tenant usage counters (dtx_serving_tenant_*); capped like
        # adapter_requests so tenant churn can't grow the exposition
        self._tenant_lock = threading.Lock()
        self.tenant_stats: Dict[str, Dict[str, int]] = {}
        self._tenant_stats_cap = 1024
        # dynamic pooled mode (adapter_pool > 0): adapters are DATA — a
        # fixed-geometry device pool + host registry with load-on-miss /
        # LRU eviction / refcount pinning (datatunerx_tpu/adapters/).
        # Constructor adapters are registered lazily; the first request (or
        # an /admin/adapters preload) materialises them into pool slots.
        self.adapter_registry = None
        self.adapter_store = None
        if adapter_pool > 0:
            from datatunerx_tpu.adapters import AdapterRegistry, AdapterStore
            from datatunerx_tpu.models.lora import DEFAULT_TARGETS

            self.adapter_store = AdapterStore(
                self.cfg, pool_slots=int(adapter_pool),
                rank_max=int(adapter_rank_max) or 8,
                targets=tuple(adapter_targets or DEFAULT_TARGETS))
            host_tier = None
            if self.host_adapter_cache_mb > 0:
                from datatunerx_tpu.tenancy import HostAdapterTier

                host_tier = HostAdapterTier(
                    int(self.host_adapter_cache_mb * 1024 * 1024))
            self.adapter_registry = AdapterRegistry(
                self.adapter_store,
                # lazy closures: both attributes exist before any load runs
                load_observer=lambda ms: self._h_adapter_load.observe(ms),
                # an async load resolving wakes the scheduler so the
                # FIFO-head admits immediately instead of on the next poll
                on_load_done=lambda: self._wake.set(),
                host_tier=host_tier)
            for aname, path in named.items():
                self.adapter_registry.register(aname, path)
            if self.tenants is not None:
                self.adapter_registry.set_pinned(
                    self.tenants.pinned_adapters())
        elif named:
            self._build_adapter_stack(named)
        # per-adapter request counters (dtx_serving_adapter_requests_total).
        # Capped, and pruned on unload: every key becomes a Prometheus
        # series, and tenant churn over weeks must not grow the exposition
        # without bound (names here passed submit's membership check, but
        # the registered population itself churns unboundedly).
        self._adapter_req_lock = threading.Lock()
        self.adapter_requests: Dict[str, int] = {}
        self._adapter_requests_cap = 1024

        self.kv_quant = kv_quant or None
        self.paged = kv_block_size > 0
        self.block_size = int(kv_block_size)
        # KV overcommit plane: admission reserves only the prompt's blocks
        # plus one tick's growth headroom, the scheduler appends blocks at
        # each slot's cursor as decode advances, prefix-cache hits map
        # SHARED refcounted blocks (copy-on-write tail), and exhaustion
        # preempts youngest-first (sessions park host-side as dtx-kv-session
        # payloads and resume token-exactly when blocks free). "off" is
        # byte-identical to the eager-reserve engine.
        oc_mode = (kv_overcommit if isinstance(kv_overcommit, str)
                   else ("on" if kv_overcommit else "off"))
        oc_mode = (oc_mode or "off").strip().lower()
        if oc_mode not in ("on", "off"):
            raise ValueError(
                f"kv_overcommit must be on|off, got {kv_overcommit!r}")
        if oc_mode == "on" and not self.paged:
            raise ValueError(
                "--kv_overcommit on requires the paged KV cache "
                "(--kv_block_size > 0)")
        self.overcommit = self.paged and oc_mode == "on"
        # Pallas in-place decode kernel (ops/pallas_paged_attention.py):
        # "auto" engages it on a real TPU backend and keeps the XLA gather
        # elsewhere (interpret-mode emulation would only slow CPU smoke
        # runs); "on" forces it anywhere — CPU tests/bench run the kernel
        # through the interpret gate — and "off" pins the gather oracle.
        # The resolved bool rides the model config so the jitted programs
        # (and the process-wide program memo key) see it.
        mode = paged_kernel if isinstance(paged_kernel, str) else \
            ("on" if paged_kernel else "off")
        mode = (mode or "auto").strip().lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"paged_kernel must be auto|on|off, got {paged_kernel!r}")
        if mode == "on" and not self.paged:
            raise ValueError(
                "--paged_kernel on requires the paged KV cache "
                "(--kv_block_size > 0)")
        self.paged_kernel = self.paged and (
            mode == "on"
            or (mode == "auto" and jax.default_backend() == "tpu"))
        if self.paged_kernel:
            import dataclasses

            self.cfg = dataclasses.replace(self.cfg, paged_kernel=True)
        # Fused on-chip sampling epilogue (ops/pallas_sampling.py): the
        # jitted decode/spec programs sample inside the traced computation
        # (greedy / temperature / exact-top-p as STATIC per-batch modes)
        # instead of handing each step's [S, vocab] logits to the legacy
        # argsort sampler. "auto" engages it on a real TPU backend only —
        # mirroring paged_kernel — "on" forces it anywhere (non-TPU runs
        # use the XLA tile-walk oracle: same math, same tokens), "off"
        # pins the legacy sampler with traced programs byte-identical to a
        # pre-epilogue build. The resolved impl keys the program memo.
        emode = (sampling_epilogue if isinstance(sampling_epilogue, str)
                 else ("on" if sampling_epilogue else "off"))
        emode = (emode or "auto").strip().lower()
        if emode not in ("auto", "on", "off"):
            raise ValueError(
                "sampling_epilogue must be auto|on|off, "
                f"got {sampling_epilogue!r}")
        self.sampling_epilogue = "on" if (
            emode == "on"
            or (emode == "auto" and jax.default_backend() == "tpu")
        ) else "off"
        self._epilogue_impl = (sampling_default_impl()
                               if self.sampling_epilogue == "on" else "off")
        # fused-path observability (dtx_serving_sampling_*): decode ticks
        # that ran a fused-epilogue program vs the legacy sampler; written
        # by the scheduler thread only, like spec_stats
        self.sampling_stats = {"fused_steps": 0, "legacy_steps": 0}
        self._allocator: Optional[BlockAllocator] = None
        if self.paged:
            if self.max_seq_len % self.block_size:
                raise ValueError(
                    f"kv_block_size {self.block_size} must divide "
                    f"max_seq_len {self.max_seq_len}")
            self.blocks_per_slot = self.max_seq_len // self.block_size
            total_blocks = int(kv_blocks or slots * self.blocks_per_slot)
            if total_blocks < self.blocks_per_slot:
                raise ValueError(
                    f"kv_blocks {total_blocks} cannot hold one full-length "
                    f"request ({self.blocks_per_slot} blocks of "
                    f"{self.block_size})")
            self._allocator = BlockAllocator(total_blocks)
            self._cache = init_paged_cache(
                self.cfg, slots, total_blocks, self.block_size,
                self.blocks_per_slot, dtype=jnp.bfloat16,
                quantize=self.kv_quant)
        else:
            self._cache = init_cache(self.cfg, slots, self.max_seq_len,
                                     dtype=jnp.bfloat16, per_slot=True,
                                     quantize=self.kv_quant)
        # chunked prefill runs in bucket-multiple programs so the compile
        # count stays bounded (chunk lengths ∈ multiples of DECODE_BUCKET)
        self.prefill_chunk = max(
            DECODE_BUCKET, -(-int(prefill_chunk) // DECODE_BUCKET) * DECODE_BUCKET)
        # the budget is a HARD bound (prefill chunks are clamped to the
        # remaining budget each tick), so round it up to the bucket quantum —
        # a sub-bucket budget could never admit a chunk and would starve
        # prefill outright
        budget = max(0, int(prefill_token_budget))
        self.prefill_token_budget = (
            -(-budget // DECODE_BUCKET) * DECODE_BUCKET if budget else 0)
        V = self.cfg.vocab_size
        self._logits = jnp.zeros((slots, V), jnp.float32)
        self._pos = jnp.zeros((slots,), jnp.int32)
        self._remaining = jnp.zeros((slots,), jnp.int32)
        self._active = jnp.zeros((slots,), bool)
        self._rng = jnp.stack([jax.random.PRNGKey(i) for i in range(slots)])
        self._temps = jnp.zeros((slots,), jnp.float32)
        self._top_ps = jnp.ones((slots,), jnp.float32)
        self._stops = jnp.full((slots, MAX_STOP), -1, jnp.int32)
        self._adapter_idx = jnp.zeros((slots,), jnp.int32)

        # ---- speculative decoding (serving/speculative.py): a draft model
        # proposes k tokens, one verify-k target forward accepts a prefix.
        # No draft configured → every spec structure stays None and the
        # scheduler takes the exact pre-spec decode path (--spec_mode off
        # is byte-identical to not having the feature).
        smode = (spec_mode or "auto").strip().lower()
        if smode not in ("auto", "on", "off"):
            raise ValueError(f"spec_mode must be auto|on|off, got {spec_mode!r}")
        if smode == "on" and not spec_draft:
            raise ValueError("--spec_mode on requires --spec_draft_config")
        self.spec_mode = smode
        self.spec_k = max(1, int(spec_k))
        self.spec = None
        self.spec_tree = None
        if spec_tree and smode != "off":
            from datatunerx_tpu.serving import speculative as spec_mod

            if not spec_draft:
                raise ValueError("--spec_tree requires --spec_draft_config")
            self.spec_tree = spec_mod.parse_spec_tree(spec_tree)
            if self.spec_tree.step_tokens >= self.max_seq_len:
                raise ValueError(
                    f"spec_tree {self.spec_tree} writes "
                    f"{self.spec_tree.step_tokens} tokens per step — does "
                    f"not fit max_seq_len {self.max_seq_len}")
        self.spec_tree_learned = bool(spec_tree_learned) and \
            self.spec_tree is not None
        # one verify step writes up to step-token-count tokens past a row's
        # cursor (chain: pending + k proposals; tree: pending + W*D nodes);
        # paged admission reserves that overshoot so every verify write
        # stays physical (ops.paged_attention.blocks_for_depth caps at the
        # table width). Sizing it from the ACTUAL per-step token count —
        # not a chain-shaped spec_k+1 — is what keeps tree mode from
        # under-reserving blocks. 0 when spec is off — reserve math
        # byte-identical to today.
        self._spec_step_tokens = (self.spec_tree.step_tokens
                                  if self.spec_tree else self.spec_k + 1)
        self._spec_overshoot = 0
        if spec_draft and smode != "off":
            from datatunerx_tpu.serving import speculative as spec_mod

            dcfg, dparams = spec_mod.build_draft(spec_draft, self.cfg,
                                                 self.params)
            self.spec = {
                "draft": spec_draft,
                "dcfg": dcfg,
                "dparams": dparams,
                # compact per-slot dense cache for the draft — rides the
                # same ops/attention.py cache interface as the target's
                "dcache": init_cache(dcfg, slots, self.max_seq_len,
                                     dtype=jnp.bfloat16, per_slot=True),
                "programs": spec_mod.spec_programs(
                    self.cfg, dcfg, self.max_seq_len, self.kv_quant,
                    epilogue=self._epilogue_impl),
            }
            # learned tree shapes (AdaptiveTree): per-depth width selection
            # from acceptance EMAs + draft-side early exit on a decisive
            # root margin. spec_tree_learned=False pins the fixed WxD
            # rectangle controller — the bench's learned-vs-fixed twin.
            ctrl_cls = (spec_mod.AdaptiveTree if self.spec_tree_learned
                        else spec_mod.AdaptiveK)
            self.spec_ctrl = ctrl_cls(self.spec_k, mode=smode,
                                      tree=self.spec_tree)
            self._spec_overshoot = self._spec_step_tokens
            self._spec_pending = jnp.zeros((slots,), jnp.int32)
            self._spec_form = [False] * slots   # slot is in pending form
            self._spec_primed = [False] * slots  # draft row holds the context
            # counters behind dtx_serving_spec_{proposed,accepted}_total and
            # the step-mix; written by the scheduler thread only
            self.spec_stats = {"proposed": 0, "accepted": 0,
                               "row_steps": 0,  # per-row verify events
                               "spec_steps": 0, "plain_steps": 0,
                               "tree_steps": 0}
            # per-adapter acceptance EMA ('' = base) for /metrics + routing
            self._spec_adapter_ema: Dict[str, float] = {}
            # per-slot accepted-path-length EMA (tree mode): pruned on
            # release like the slot acceptance EMAs, capped on export
            self._spec_tree_slot_path: Dict[int, float] = {}
            self._h_accept_len = None  # bound after the registry exists

        # ---- overcommit scheduler state. _tick_advance = the most cache
        # lanes one scheduler tick can consume per slot (a plain decode
        # chunk, or a verify step — chain or tree), and growth must
        # additionally keep the spec write overshoot physical — together
        # the per-tick capacity target the grower maintains ahead of every
        # cursor.
        self._tick_advance = self.chunk
        if self.spec is not None:
            self._tick_advance = max(self.chunk, self._spec_step_tokens)
        # preempted sessions, parked host-side as dtx-kv-session payloads
        # (raw-numpy bodies — no b64 for in-process parking), oldest first;
        # owned by the scheduler thread
        self._preempted: List[dict] = []
        # dtx_serving_preemptions_total{outcome} source (scheduler-only
        # writes; scraped racily like every other stats dict)
        self.preempt_stats: Dict[str, int] = {}
        # capacity observability for DTX_BENCH_SERVE_CAPACITY: the high-water
        # mark of concurrently admitted sessions and each finished session's
        # physical block footprint (== its peak: tables only ever grow)
        self.kv_stats = {"peak_sessions": 0,
                         "session_blocks": collections.deque(maxlen=4096)}
        # per-slot EAGER-equivalent reserve (what the overcommit-off engine
        # would hold) — the dtx_serving_kv_overcommit_ratio numerator
        self._slot_demand: List[int] = [0] * slots
        # chat-encode LRU (see _encode_chat): HTTP threads share it
        self._encode_memo: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self._encode_memo_lock = threading.Lock()
        self._slot_req: List[Optional[Request]] = [None] * slots
        self._slot_blocks: List[List[int]] = [[] for _ in range(slots)]
        # dynamic mode: the adapter NAME each slot pins (released with the
        # slot, so LRU eviction can never pull weights out from under an
        # in-flight decode)
        self._slot_adapter: List[Optional[str]] = [None] * slots
        self._decode_ready: List[bool] = [False] * slots
        # slot → in-progress chunked-prefill state, in admission order
        self._pending: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self._waiting: "queue.Queue[Request]" = queue.Queue()
        # requests that must admit BEFORE anything in _waiting (FIFO order
        # preserved): the block-starved head, and adapter-loading requests
        # parked while their checkpoint reads run on loader threads
        self._waiting_front: "collections.deque[Request]" = collections.deque()
        self._last_adapter_wait: Optional[str] = None  # wait-trace dedupe
        self._admit_wait_reason = ""  # why the last _admit returned False
        self._wake = threading.Event()
        self._shutdown = threading.Event()
        # KV migration fabric (serving/migration.py): export/import commands
        # from admin HTTP threads, serviced by the scheduler between decode
        # chunks — the scheduler owns every piece of slot state, so commands
        # queue to it instead of locking it. Imports facing a transient
        # shortage (free slot, KV blocks, adapter mid-load) park in
        # _mig_retry and re-run next tick until their deadline.
        self._mig_q: "queue.Queue[dict]" = queue.Queue()
        self._mig_retry: List[dict] = []
        # outcome counters behind dtx_serving_session_{export,import}_total
        self.session_stats: Dict[str, Dict[str, int]] = {
            "export": {}, "import": {}}
        # scheduler-tick trace, for tests and TTFT/TPOT forensics:
        # ("admit", slot, plen, mode) / ("prefill", slot, ntokens) /
        # ("activate", slot) / ("decode", K) / ("finish", slot)
        self.sched_trace: "collections.deque[tuple]" = \
            collections.deque(maxlen=4096)

        # Process-wide program memo (the Trainer step-memo pattern,
        # training/train_lib.py): engines built from an equal (model config,
        # max_seq_len, kv_quant) trace identical programs — everything else
        # the jitted fns touch arrives as an argument, and dense/paged/
        # slot-count/ADAPTER variation lives in argument shapes/structure
        # jax already keys on — so they share one _Programs holder and with
        # it jax's in-memory executable cache. Side-by-side paged/dense
        # engines (parity tests, the serve bench's paged-vs-dense runs,
        # blue/green replica swaps in one process) compile each program once
        # instead of once per engine; doubly important on jax 0.4.x where
        # the persistent compile cache is unusable (tests/conftest.py).
        # Adapters no longer enter the key at all: the stacked tree / pool
        # is a program ARGUMENT, so engines with any adapter mapping share
        # programs, and the dynamic pool serves load/unload with ZERO
        # recompiles (the geometry fixes every leaf shape up front).
        key = _program_memo_key(self.cfg, self.max_seq_len, self.kv_quant,
                                self._epilogue_impl)
        progs = None if key is None else _PROGRAM_MEMO.get(key)
        if progs is None:
            progs = _Programs(self.cfg, self.max_seq_len, self.kv_quant,
                              self._epilogue_impl)
            if key is not None:
                _PROGRAM_MEMO[key] = progs
                while len(_PROGRAM_MEMO) > _PROGRAM_MEMO_MAX:
                    _PROGRAM_MEMO.popitem(last=False)
        else:
            _PROGRAM_MEMO.move_to_end(key)
        self._prefill = progs.prefill
        self._extend = progs.extend
        self._insert = progs.insert
        self._insert_paged = progs.insert_paged
        self._activate = progs.activate
        self._prefill_chunk_fn = progs.prefill_chunk
        self._extract = progs.extract
        self._copy_block = progs.copy_block
        self._decode = progs.decode

        self._prefix = _PrefixCache(
            prefix_cache, on_evict=self._free_prefix_entry
        ) if prefix_cache > 0 else None
        # COW prefix blocks: overcommit engines with a prefix cache store
        # refcounted BLOCK entries — hits map shared physical blocks into
        # the new slot's table instead of the dense-row copy + re-insert
        self.cow = self.overcommit and self._prefix is not None
        # keep-warm (fleet plane, off by default = byte-identical engine):
        # a preempted/drained slot publishes its prompt blocks as a
        # no_reuse prefix entry before freeing, so the prompt survives the
        # park as a COW-extendable prefix instead of dying with the slot.
        # Requires COW entries (the publish is a block incref + tail copy).
        self.prefix_keep_warm = bool(prefix_keep_warm) and self.cow
        # slot → (prefix-cache key, prompt cursor) of the prompt the slot
        # holds — what keep-warm publishes at preemption time
        self._slot_key: List[Optional[tuple]] = [None] * slots
        # observability: how admissions were served (tests + /metrics)
        self.prefill_stats = {"full": 0, "reuse": 0, "extend": 0}
        # Shared-registry latency histograms. Recording is BUFFERED off the
        # hot path: token stamps are plain attribute writes in Request.push;
        # the observes below fire once per completed request (TTFT/TPOT) or
        # once per prefill chunk — never per token.
        self.registry = registry or Registry()
        (self._h_ttft, self._h_tpot,
         self._h_prefill_chunk) = serving_latency_histograms(self.registry)
        self._h_adapter_load = adapter_load_histogram(self.registry)
        if self.spec is not None:
            from datatunerx_tpu.obs.metrics import spec_accept_len_histogram

            self._h_accept_len = spec_accept_len_histogram(self.registry)
        # Per-request span timelines (the PR 5 sched_trace deque, promoted):
        # completed requests land in a bounded trace ring keyed by trace id,
        # served by GET /debug/trace/<id> on the serving server and merged
        # into the gateway's trace view.
        self.tracing = tracing
        self.trace_store = TraceStore(capacity=trace_ring,
                                      jsonl_path=trace_log_path)

        self._thread = threading.Thread(target=self._scheduler, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ block pool
    @property
    def decode_path(self) -> str:
        """How decode attention reads the KV cache: ``pallas`` (in-place
        block-table kernel), ``gather`` (paged XLA oracle), or ``dense``."""
        if not self.paged:
            return "dense"
        return "pallas" if self.paged_kernel else "gather"

    @property
    def total_kv_blocks(self) -> Optional[int]:
        return self._allocator.num_blocks if self._allocator else None

    @property
    def free_kv_blocks(self) -> Optional[int]:
        return self._allocator.free_count if self._allocator else None

    @property
    def kv_blocks_reserved(self) -> Optional[int]:
        if self._allocator is None:
            return None
        return self._allocator.num_blocks - self._allocator.free_count

    @property
    def kv_overcommit_ratio(self) -> Optional[float]:
        """Live sessions' EAGER-equivalent block demand over the physical
        pool: > 1.0 means the engine has admitted more logical reserve than
        HBM holds — the whole point of on-demand growth. None on dense
        engines (no block signal)."""
        if self._allocator is None:
            return None
        demand = sum(self._slot_demand[s] for s in range(self.slots)
                     if self._slot_req[s] is not None)
        return round(demand / max(1, self._allocator.num_blocks), 4)

    @property
    def parked_sessions(self) -> int:
        """Preemption-parked sessions awaiting local resume — what the
        fleet spill coordinator polls (via /stats) to find re-homing
        candidates. Host-side list length; safe from any thread."""
        return len(self._preempted)

    def _free_prefix_entry(self, ent: dict):
        """Prefix-cache eviction hook: return a COW block entry's refs to
        the allocator (dense-row entries hold no pool resources). Runs on
        whichever thread evicted (scheduler put, admin drop_adapter) —
        the allocator's own lock covers it."""
        blocks = ent.get("blocks")
        if blocks and self._allocator is not None:
            self._allocator.free(blocks)

    def _count_preempt(self, outcome: str):
        self.preempt_stats[outcome] = self.preempt_stats.get(outcome, 0) + 1

    # ------------------------------------------------------------- adapters
    def _build_adapter_stack(self, named: Dict[str, str]):
        """Stack named adapter checkpoints into [L, E, …] leaves (entry 0 is
        the all-zero base adapter). Mixed ranks are padded to the max rank
        (zero cols/rows leave the delta unchanged); mixed target sets take
        the union with zeros where an adapter lacks a target."""
        from datatunerx_tpu.models.lora import target_dims

        loaded: List[Tuple[str, dict, float]] = []
        for name, path in named.items():
            state = load_checkpoint_state(path)
            lora = state.get("lora")
            if not lora:
                raise ValueError(f"adapter {name!r}: no lora tree in {path}")
            layers = lora["layers"]
            rank = next(iter(layers.values()))["a"].shape[-1]
            scaling = state.get("_scaling")
            if scaling is None:
                scaling = lora_scaling(32.0, rank)
            loaded.append((name, layers, float(scaling)))

        targets = sorted({t for _, layers, _ in loaded for t in layers}
                         & set(LORA_TARGETS))
        max_rank = max(
            layers[t]["a"].shape[-1]
            for _, layers, _ in loaded for t in layers
        )
        L = self.cfg.num_layers
        E = len(loaded) + 1  # + base zero adapter
        stack: Dict[str, dict] = {}
        for t in targets:
            d_in, d_out = target_dims(self.cfg, t)
            a = np.zeros((L, E, d_in, max_rank), np.float32)
            b = np.zeros((L, E, max_rank, d_out), np.float32)
            for e, (_, layers, _) in enumerate(loaded, start=1):
                if t not in layers:
                    continue
                ar = np.asarray(layers[t]["a"], np.float32)  # [L, d_in, r]
                br = np.asarray(layers[t]["b"], np.float32)
                r = ar.shape[-1]
                a[:, e, :, :r] = ar
                b[:, e, :r, :] = br
            stack[t] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        scales = jnp.asarray([0.0] + [s for _, _, s in loaded], jnp.float32)
        self.lora_stack = ({"layers": stack}, scales)
        for e, (name, _, _) in enumerate(loaded, start=1):
            self._static_adapter_ids[name] = e

    @property
    def adapter_ids(self) -> Dict[str, int]:
        """Known adapter names → device index. Static mode: the fixed
        stack's name→index binding. Dynamic mode: every REGISTERED name
        (resident → its pool slot, loadable-on-miss → -1) — membership is
        what the serving server and gateway check."""
        if self.adapter_registry is not None:
            return self.adapter_registry.id_map()
        return self._static_adapter_ids

    def _lora_arg(self):
        """The programs' ``lora`` argument: None (base-only), the static
        stacked tree, or the dynamic pool's atomically-republished
        snapshot (one attribute read — no lock on the decode hot path)."""
        if self.adapter_store is not None:
            return self.adapter_store.tree
        return self.lora_stack

    # ---- dynamic pool control plane (serving /admin/adapters backs these)
    def load_adapter(self, name: str, checkpoint_path: str,
                     preload: bool = True) -> dict:
        """Register (and by default warm) an adapter at runtime. Raises
        NotImplementedError when the engine runs a static stack,
        ValueError / AdapterRankError for a checkpoint the pool geometry
        rejects, RuntimeError on transient pool exhaustion, and
        AdapterPinnedError when re-registering a live name."""
        if self.adapter_registry is None:
            raise NotImplementedError(
                "engine runs a static adapter stack; restart with "
                "--adapter_pool to load adapters at runtime")
        existed = name in self.adapter_registry.names()
        if existed:
            rebound = (self.adapter_registry.describe(name)["checkpoint"]
                       != checkpoint_path)
        self.adapter_registry.register(name, checkpoint_path)
        if existed and rebound and self._prefix is not None:
            # same name, different weights: cached rows are stale
            self._prefix.drop_adapter(name)
        if preload:
            try:
                self.adapter_registry.preload(name)
            except (ValueError, FileNotFoundError):
                # a bad CHECKPOINT must not stay registered (every later
                # request would hit the same error at admission) — but
                # only roll back a registration THIS call created;
                # transient failures (pool exhausted) never unregister
                if not existed:
                    self.adapter_registry.unregister(name)
                raise
        return self.adapter_registry.describe(name)

    def unload_adapter(self, name: str) -> bool:
        """Evict + unregister. AdapterPinnedError while in-flight requests
        still decode with it (the admin plane answers 409)."""
        if self.adapter_registry is None:
            raise NotImplementedError("engine runs a static adapter stack")
        gone = self.adapter_registry.unregister(name)
        if gone:
            if self._prefix is not None:
                # the name may be re-registered with different weights
                # later — rows cached under it must not survive the
                # unbinding
                self._prefix.drop_adapter(name)
            with self._adapter_req_lock:
                # the tenant is gone; its counter series goes with it
                self.adapter_requests.pop(name, None)
        return gone

    def adapter_occupancy(self) -> Optional[dict]:
        """Pool occupancy + registry stats for stats()//metrics; None on
        static/base engines (no pool to report)."""
        if self.adapter_registry is None:
            return None
        occ = self.adapter_registry.occupancy()
        occ["resident_adapters"] = sorted(self.adapter_registry.resident())
        occ["registered_adapters"] = self.adapter_registry.names()
        occ["load_ms"] = list(self.adapter_registry.load_ms)
        with self._adapter_req_lock:
            occ["requests"] = dict(self.adapter_requests)
        return occ

    @property
    def resident_adapters(self) -> Optional[Dict[str, int]]:
        if self.adapter_registry is None:
            return None
        return self.adapter_registry.resident()

    # ------------------------------------------------------------- tenancy
    def _tenant_count(self, tenant: str, key: str, n: int):
        """Bump a per-tenant usage counter under the cap (the PR 10
        adapter_requests pattern): known tenants always count, new label
        values stop landing once 1024 distinct tenants exist — a client-
        controlled header must not grow the exposition unboundedly."""
        with self._tenant_lock:
            row = self.tenant_stats.get(tenant)
            if row is None:
                if len(self.tenant_stats) >= self._tenant_stats_cap:
                    return
                row = self.tenant_stats[tenant] = {
                    "requests": 0, "tokens_in": 0, "tokens_out": 0}
            row[key] = row.get(key, 0) + n

    def tenant_usage(self) -> Optional[dict]:
        """Per-tenant usage + live occupancy for stats()//metrics, or None
        when the tenancy plane is off (consumers gate their exposition on
        this, keeping the no-config scrape byte-identical)."""
        if self.tenants is None:
            return None
        with self._tenant_lock:
            usage = {t: dict(row) for t, row in self.tenant_stats.items()}
        # live KV blocks per tenant: racy slot-list reads, same contract
        # as every other scrape-path stats read
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None or not getattr(req, "tenant", ""):
                continue
            row = usage.setdefault(
                req.tenant,
                {"requests": 0, "tokens_in": 0, "tokens_out": 0})
            row["kv_blocks"] = (row.get("kv_blocks", 0)
                                + len(self._slot_blocks[s]))
        # adapter residency per tenant (how many of the tenant's adapters
        # are pool-resident right now)
        resident = set(self.adapter_registry.resident()) \
            if self.adapter_registry is not None else set()
        for name in self.tenants.names():
            spec = self.tenants.get(name)
            if spec is None:
                continue
            row = usage.setdefault(
                name, {"requests": 0, "tokens_in": 0, "tokens_out": 0})
            row["tier"] = spec.tier
            row["adapters_resident"] = len(resident & set(spec.adapters))
        return usage

    def refresh_tenant_pins(self):
        """Re-sync the registry's pin set after a directory change (the
        serving admin plane calls this on tenant upserts)."""
        if self.tenants is not None and self.adapter_registry is not None:
            self.adapter_registry.set_pinned(self.tenants.pinned_adapters())

    # ------------------------------------------------------------ scheduler
    def _prefix_key(self, ids, plen, n_prompt, akey):
        return (tuple(ids[plen - n_prompt:]), akey)

    def _adapter_cache_key(self, req: Request):
        """Prefix-cache adapter identity. Dynamic mode keys by NAME: a pool
        slot index is recycled across evict/reload (same name can land on a
        different slot, different name on the same slot), but cached KV rows
        depend only on the adapter's weights — the name is the stable
        identity. Static mode keeps the stack index (bijective with the
        name for the engine's lifetime)."""
        if self.adapter_registry is not None:
            return req.adapter_name
        return req.adapter

    def _prefill_row_cached(self, ids, plen, n_prompt, adapter, akey,
                            budget_needed: int):
        """Prefix-cache paths only: (logits, dense row, cursor) on an exact
        hit (no compute) or a strict-prefix hit (suffix-only extension);
        None on miss or when the cache is disabled. ``adapter`` is the
        device pool/stack index, ``akey`` the cache-key identity.

        Reuse must never change the response: a cached row whose cursor sits
        deeper than this request's own plen (extension padding accumulates)
        is only used when it still leaves ``budget_needed`` decode room —
        otherwise the cold path runs, so budget and output match a cache-cold
        server exactly."""
        if self._prefix is None:
            return None
        used, _ = key = self._prefix_key(ids, plen, n_prompt, akey)
        # the decode room the cold path would provide; reuse may not shrink
        # the effective budget below min(requested, cold)
        need = min(budget_needed, self.max_seq_len - plen)
        ent = self._prefix.get(key)
        # no_reuse entries (keep-warm publishes, logits-free tier imports)
        # carry no activation logits — they serve strict-prefix extension
        # only, never the exact-hit fast path
        if (ent is not None and not ent.get("no_reuse")
                and self.max_seq_len - ent["cursor"] >= need):
            self.prefill_stats["reuse"] += 1
            return ent["logits"], ent["cache"], ent["cursor"]
        pkey, pent = self._prefix.longest_prefix(used, akey)
        if pent is not None:
            n_pref = len(pkey[0])
            suffix = list(used[n_pref:])
            pad = (-len(suffix)) % DECODE_BUCKET
            stoks = [self.tokenizer.eos_token_id or 0] * pad + suffix
            smask = [0] * pad + [1] * len(suffix)
            spos = [0] * pad + list(range(n_pref, len(used)))
            cursor = pent["cursor"] + len(stoks)
            if self.max_seq_len - cursor >= need:
                row_logits, row_cache = self._extend(
                    self.params, self._lora_arg(),
                    _pad_row(pent["cache"], self.max_seq_len),
                    jnp.asarray([stoks], jnp.int32),
                    jnp.asarray([smask], jnp.int32),
                    jnp.asarray([spos], jnp.int32),
                    jnp.asarray(adapter, jnp.int32),
                    suffix_len=len(stoks),
                )
                self.prefill_stats["extend"] += 1
                self._prefix.put(key, {"cache": row_cache,
                                       "logits": row_logits,
                                       "cursor": cursor})
                return row_logits, row_cache, cursor
        return None

    def _prefill_row(self, ids, mask, positions, plen, n_prompt, adapter,
                     akey, budget_needed: int = 1):
        """Produce (last-token logits, row cache, cache cursor) for a prompt,
        going through the prefix cache when enabled: exact hit = no compute,
        prefix hit = suffix-only extension, miss = full prefill (+ store)."""
        hit = self._prefill_row_cached(ids, plen, n_prompt, adapter, akey,
                                       budget_needed)
        if hit is not None:
            return hit
        row_logits, row_cache = self._prefill(
            self.params, self._lora_arg(), jnp.asarray([ids], jnp.int32),
            jnp.asarray([mask], jnp.int32), jnp.asarray([positions], jnp.int32),
            jnp.asarray(adapter, jnp.int32), prompt_len=plen,
        )
        self.prefill_stats["full"] += 1
        if self._prefix is not None:
            self._prefix.put(self._prefix_key(ids, plen, n_prompt, akey),
                             {"cache": row_cache, "logits": row_logits,
                              "cursor": plen})
        return row_logits, row_cache, plen

    @staticmethod
    def _stop_row(req: Request) -> np.ndarray:
        row = np.full((MAX_STOP,), -1, np.int32)
        row[: len(req.stop_ids)] = req.stop_ids
        return row

    def _arm_args(self, req: Request, n_prompt: int, max_new: int):
        """The per-slot decode-state scalars _insert/_insert_paged/_activate
        all share."""
        return (
            jnp.asarray(n_prompt, jnp.int32), jnp.asarray(max_new, jnp.int32),
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.top_p, jnp.float32),
            jnp.asarray(self._stop_row(req)),
            jnp.asarray(req.adapter, jnp.int32),
            jnp.asarray(req.seed, jnp.uint32),
        )

    def _admit(self, req: Request, slot: int) -> bool:
        """Occupy ``slot`` with ``req``, resolving (and PINNING) its
        adapter first in dynamic mode — load-on-miss runs here, and a
        fully-pinned pool FIFO-waits exactly like KV-block exhaustion.
        False = some pool (adapter slots or KV blocks) is exhausted; the
        request stays queued with nothing held."""
        pinned = False
        self._admit_wait_reason = "blocks"
        if self.adapter_registry is not None and req.adapter_name:
            if req.adapter_was_resident is None:
                req.adapter_was_resident = (
                    req.adapter_name in self.adapter_registry.resident())
            # non-blocking: a miss kicks an ASYNC load and returns None —
            # decode keeps ticking while the checkpoint reads; the request
            # parks at its FIFO position until the load resolves
            idx = self.adapter_registry.acquire(
                req.adapter_name, count_hit=not req.adapter_stats_counted)
            if idx is not None:
                req.adapter_stats_counted = True
            if idx is None:
                loading = self.adapter_registry.describe(
                    req.adapter_name).get("loading", False)
                # mid-load → "adapter": younger requests may bypass (the
                # head's pool slot is already reserved). Pool fully pinned
                # → strict FIFO like blocks: bypassers could re-pin
                # residents forever and starve the head's eviction.
                self._admit_wait_reason = ("adapter" if loading
                                           else "adapter_pool")
                if self._last_adapter_wait != req.adapter_name:
                    # dedupe: one trace entry per wait episode, not one
                    # per scheduler retry tick (would flood the ring)
                    self._trace("adapter_wait", req.adapter_name)
                    self._last_adapter_wait = req.adapter_name
                return False
            self._last_adapter_wait = None
            pinned = True
            req.adapter = idx
            if self.tracing:
                req.mark("adapter", name=req.adapter_name, slot=idx,
                         loaded=not req.adapter_was_resident)
        try:
            ok = self._admit_slot(req, slot)
        except Exception:
            if pinned:
                self.adapter_registry.release(req.adapter_name)
            raise
        if ok:
            if pinned:
                self._slot_adapter[slot] = req.adapter_name
        elif pinned:
            self.adapter_registry.release(req.adapter_name)
        return ok

    def _admit_slot(self, req: Request, slot: int) -> bool:
        """Occupy ``slot`` with ``req``. Dense mode prefills monolithically
        and arms the slot at once. Paged mode reserves blocks first (False =
        pool exhausted; the request stays queued), serves prefix-cache hits
        by scattering the row into the blocks, and registers everything else
        for chunked prefill interleaved with decode."""
        from datatunerx_tpu.utils.decoding import prepare_prompt

        ids, mask, positions, plen, n_prompt, max_new, _ = prepare_prompt(
            req.prompt_ids, self.tokenizer.eos_token_id,
            self.max_seq_len, req.max_new_tokens,
        )
        # the real (un-padded) kept prompt: what the draft model prefills
        # when this slot later joins speculative decoding
        req.spec_prime_ids = ids[plen - n_prompt:]
        akey = self._adapter_cache_key(req)
        if not self.paged:
            row_logits, row_cache, cursor = self._prefill_row(
                ids, mask, positions, plen, n_prompt, req.adapter, akey,
                budget_needed=max_new)
            max_new = max(1, min(max_new, self.max_seq_len - cursor))
            (self._cache, self._logits, self._pos, self._remaining,
             self._active, self._temps, self._top_ps, self._stops,
             self._adapter_idx, self._rng) = self._insert(
                self._cache, self._logits, self._pos, self._remaining,
                self._active, self._temps, self._top_ps, self._stops,
                self._adapter_idx, self._rng,
                jnp.asarray(slot, jnp.int32), row_cache, row_logits,
                # the slot's write cursor continues from the row's real KV
                # depth (prefix reuse can sit deeper than this request's plen)
                jnp.asarray(cursor, jnp.int32),
                *self._arm_args(req, n_prompt, max_new),
            )
            self._slot_req[slot] = req
            self._decode_ready[slot] = True
            self._note_admitted(slot)
            self._trace("admit", slot, plen, "dense")
            if self.tracing:
                req.mark("admit", slot=slot, plen=plen, mode="dense")
            return True

        if self.cow:
            # COW prefix blocks: a cache hit maps SHARED physical blocks
            # into this slot's table (copying only the partial tail block)
            # instead of the dense-row copy + re-insert below. None = no
            # usable entry — fall through to the cold chunked path.
            handled = self._admit_cow(req, slot, ids, plen, n_prompt,
                                      max_new, akey)
            if handled is not None:
                return handled
        else:
            hit = self._prefill_row_cached(ids, plen, n_prompt, req.adapter,
                                           akey, budget_needed=max_new)
            if hit is not None:
                row_logits, row_cache, cursor = hit
                max_new = max(1, min(max_new, self.max_seq_len - cursor))
                blocks = self._alloc_blocks(
                    self._reserve_depth(cursor, max_new))
                if blocks is None:
                    return False
                try:
                    # scrub first: stored rows are TRIMMED to their live
                    # cursor now, so the insert no longer doubles as the
                    # whole-table recycled-position scrub
                    self._cache["pos"] = self._cache["pos"].at[
                        jnp.asarray(blocks, jnp.int32)].set(POS_SENTINEL)
                    (self._cache, self._logits, self._pos, self._remaining,
                     self._active, self._temps, self._top_ps, self._stops,
                     self._adapter_idx, self._rng) = self._insert_paged(
                        self._cache, self._logits, self._pos,
                        self._remaining, self._active, self._temps,
                        self._top_ps, self._stops,
                        self._adapter_idx, self._rng,
                        jnp.asarray(slot, jnp.int32),
                        self._table_row(blocks),
                        row_cache, row_logits,
                        jnp.asarray(cursor, jnp.int32),
                        *self._arm_args(req, n_prompt, max_new),
                    )
                except Exception:
                    self._allocator.free(blocks)
                    raise
                self._slot_blocks[slot] = blocks
                self._slot_req[slot] = req
                self._decode_ready[slot] = True
                self._slot_demand[slot] = self._eager_demand(cursor, max_new)
                self._note_admitted(slot)
                self._trace("admit", slot, plen, "cache")
                if self.tracing:
                    req.mark("admit", slot=slot, plen=plen, mode="cache")
                return True

        blocks = self._alloc_blocks(self._reserve_depth(plen, max_new))
        if blocks is None:
            return False
        try:
            # install the table, scrub the blocks' recycled positions to the
            # sentinel (chunked prefill reveals the whole table to attention
            # before every lane is written), and rewind the slot cursor
            self._cache["block_tables"] = \
                self._cache["block_tables"].at[slot].set(self._table_row(blocks))
            self._cache["pos"] = self._cache["pos"].at[
                jnp.asarray(blocks, jnp.int32)].set(POS_SENTINEL)
            self._cache["len"] = self._cache["len"].at[slot].set(0)
        except Exception:
            self._allocator.free(blocks)
            raise
        self._slot_blocks[slot] = blocks
        self._slot_req[slot] = req
        self._decode_ready[slot] = False
        self._slot_demand[slot] = self._eager_demand(plen, max_new)
        self._pending[slot] = {
            "req": req, "ids": ids, "mask": mask, "positions": positions,
            "plen": plen, "n_prompt": n_prompt, "max_new": max_new,
            "adapter": req.adapter, "done": 0, "base": 0,
            "key": self._prefix_key(ids, plen, n_prompt, akey),
        }
        self._note_admitted(slot)
        self._trace("admit", slot, plen, "chunked")
        if self.tracing:
            req.mark("admit", slot=slot, plen=plen, mode="chunked")
        return True

    def _reserve_depth(self, cursor: int, max_new: int) -> int:
        """Token depth admission reserves blocks for: the full decode
        extent eagerly, or just the context plus one scheduler tick's
        advance when overcommitted (the grower keeps the table ahead of
        the cursor from there; the spec overshoot rides on top inside
        ``_alloc_blocks``)."""
        if self.overcommit:
            return cursor + min(max_new, self._tick_advance)
        return cursor + max_new

    def _eager_demand(self, cursor: int, max_new: int) -> int:
        """Blocks the overcommit-OFF engine would reserve for this session
        — the dtx_serving_kv_overcommit_ratio numerator."""
        return blocks_for_depth(cursor + max_new, self.block_size,
                                overshoot=self._spec_overshoot,
                                cap_depth=self.max_seq_len)

    def _note_admitted(self, slot: int):
        live = sum(1 for r in self._slot_req if r is not None)
        if live > self.kv_stats["peak_sessions"]:
            self.kv_stats["peak_sessions"] = live

    # ------------------------------------------------- COW prefix blocks
    def _admit_cow(self, req: Request, slot: int, ids, plen: int,
                   n_prompt: int, max_new: int, akey) -> Optional[bool]:
        """Overcommit admission through the prefix cache: an exact hit
        maps the entry's refcounted blocks into this slot's table and arms
        decode directly (no prefill, no dense-row traffic); a strict-prefix
        hit maps the shared prefix and chunk-prefills only the suffix in
        place. Returns True (admitted) / False (blocks exhausted — the
        FIFO head waits) / None (no usable entry — cold path). The same
        decode-room gates as the dense-row path apply, so reuse never
        shrinks the budget below what a cache-cold server would grant."""
        used, _ = key = self._prefix_key(ids, plen, n_prompt, akey)
        need = min(max_new, self.max_seq_len - plen)
        ent = self._prefix.get(key)
        if (ent is not None and ent.get("blocks") is not None
                and not ent.get("no_reuse")
                and self.max_seq_len - ent["cursor"] >= need):
            m = max(1, min(max_new, self.max_seq_len - ent["cursor"]))
            ok = self._cow_map(req, slot, ent, n_prompt, m,
                               suffix=None, key=key)
            if ok:
                self.prefill_stats["reuse"] += 1
                self._trace("admit", slot, plen, "cow")
                if self.tracing:
                    req.mark("admit", slot=slot, plen=plen, mode="cow")
            return ok
        pkey, pent = self._prefix.longest_prefix(used, akey)
        if pent is not None and pent.get("blocks") is not None:
            n_pref = len(pkey[0])
            suffix = list(used[n_pref:])
            pad = (-len(suffix)) % DECODE_BUCKET
            eos = self.tokenizer.eos_token_id or 0
            sfx = {"ids": [eos] * pad + suffix,
                   "mask": [0] * pad + [1] * len(suffix),
                   "positions": [0] * pad + list(range(n_pref, len(used)))}
            cursor = pent["cursor"] + len(sfx["ids"])
            if self.max_seq_len - cursor >= need:
                ok = self._cow_map(req, slot, pent, n_prompt, max_new,
                                   suffix=sfx, key=key)
                if ok:
                    self.prefill_stats["extend"] += 1
                    self._trace("admit", slot, plen, "cow_extend")
                    if self.tracing:
                        req.mark("admit", slot=slot, plen=plen,
                                 mode="cow_extend")
                return ok
        return None

    def _cow_map(self, req: Request, slot: int, ent: dict, n_prompt: int,
                 max_new: int, suffix: Optional[dict], key) -> bool:
        """Install a prefix-cache BLOCK entry into ``slot``: incref and map
        the entry's full blocks, copy its partial tail block (the at-most-
        once COW event — decode only appends at the cursor, and the cursor
        sits inside that block), allocate fresh blocks for the decode/
        suffix extent, and either arm decode (exact hit) or register the
        suffix for chunked prefill. False = pool can't cover the fresh
        blocks; nothing held."""
        base = ent["cursor"]  # host int: _cow_store stores python scalars
        full, rem = ent["full"], ent["rem"]
        shared = list(ent["blocks"][:full])
        suffix_len = len(suffix["ids"]) if suffix else 0
        final = base + suffix_len
        target = blocks_for_depth(
            self._reserve_depth(final, max_new), self.block_size,
            overshoot=self._spec_overshoot, cap_depth=self.max_seq_len)
        own = self._allocator.alloc(target - full)  # >= 1: max_new >= 1
        if own is None:
            return False
        self._allocator.incref(shared)
        blocks = shared + own
        try:
            self._cache["pos"] = self._cache["pos"].at[
                jnp.asarray(own, jnp.int32)].set(POS_SENTINEL)
            if rem:
                self._cache = self._copy_block(
                    self._cache, jnp.asarray(ent["blocks"][full], jnp.int32),
                    jnp.asarray(own[0], jnp.int32),
                    jnp.asarray(rem, jnp.int32))
            self._cache["block_tables"] = self._cache["block_tables"].at[
                slot].set(self._table_row(blocks))
            self._cache["len"] = self._cache["len"].at[slot].set(base)
            if suffix is None:
                (self._logits, self._pos, self._remaining, self._active,
                 self._temps, self._top_ps, self._stops, self._adapter_idx,
                 self._rng) = self._activate(
                    self._logits, self._pos, self._remaining, self._active,
                    self._temps, self._top_ps, self._stops,
                    self._adapter_idx, self._rng,
                    jnp.asarray(slot, jnp.int32), ent["logits"],
                    *self._arm_args(req, n_prompt, max_new),
                )
        except Exception:
            self._allocator.free(blocks)
            raise
        self._slot_blocks[slot] = blocks
        self._slot_req[slot] = req
        self._slot_demand[slot] = self._eager_demand(final, max_new)
        self._slot_key[slot] = (key, final)
        if suffix is None:
            self._decode_ready[slot] = True
        else:
            self._decode_ready[slot] = False
            self._pending[slot] = {
                "req": req, "ids": suffix["ids"], "mask": suffix["mask"],
                "positions": suffix["positions"],
                "plen": len(suffix["ids"]), "n_prompt": n_prompt,
                "max_new": max_new, "adapter": req.adapter, "done": 0,
                "key": key, "base": base,
            }
        self._note_admitted(slot)
        return True

    def _cow_store(self, slot: int, key, cursor: int, row_logits):
        """Publish a freshly-prefilled slot's prefix into the cache as a
        refcounted BLOCK entry: full blocks are shared as-is (their content
        and global pos-pool rows never change again — writes only happen
        at and past the cursor), the partial tail block is copied once so
        the donor's continued decode cannot leak into the entry. A pool
        too tight for the tail copy skips caching: serving beats caching."""
        full, rem = divmod(cursor, self.block_size)
        blocks = self._slot_blocks[slot]
        shared = list(blocks[:full])
        ent_blocks = list(shared)
        if rem:
            tail = self._allocator.alloc(1)
            if tail is None:
                return
            self._cache = self._copy_block(
                self._cache, jnp.asarray(blocks[full], jnp.int32),
                jnp.asarray(tail[0], jnp.int32), jnp.asarray(rem, jnp.int32))
            ent_blocks = shared + tail
        self._allocator.incref(shared)
        self._prefix.put(key, {"blocks": ent_blocks, "full": full,
                               "rem": rem, "cursor": cursor,
                               "logits": row_logits})

    def _keep_warm(self, slot: int):
        """Publish the slot's PROMPT prefix into the prefix cache as a
        no-reuse COW block entry right before the slot is released
        (preemption / drain export), so a resume — here or on a peer —
        admits via a COW strict-prefix hit instead of re-paying the
        prefix prefill. No logits are stored: exact-hit arming needs the
        prompt's last-token logits, which a slot that has decoded past
        its prompt no longer has, hence ``no_reuse``. Best-effort — a
        missing key, an existing entry, or a pool too tight for the tail
        copy all skip silently (serving beats caching)."""
        sk = self._slot_key[slot]
        if sk is None:
            return
        key, pcursor = sk
        if self._prefix.get(key) is not None:
            return
        full, rem = divmod(pcursor, self.block_size)
        blocks = self._slot_blocks[slot]
        if len(blocks) < full + (1 if rem else 0):
            return
        shared = list(blocks[:full])
        ent_blocks = list(shared)
        if rem:
            tail = self._allocator.alloc(1)
            if tail is None:
                return
            # decode lanes past the prompt cursor live at offsets >= rem
            # of the tail block — the COW copy scrubs them in the copy
            self._cache = self._copy_block(
                self._cache, jnp.asarray(blocks[full], jnp.int32),
                jnp.asarray(tail[0], jnp.int32),
                jnp.asarray(rem, jnp.int32))
            ent_blocks = shared + tail
        self._allocator.incref(shared)
        self._prefix.put(key, {"blocks": ent_blocks, "full": full,
                               "rem": rem, "cursor": pcursor,
                               "logits": None, "no_reuse": True})
        self._trace("keep_warm", slot, pcursor)

    def _alloc_blocks(self, depth: int) -> Optional[List[int]]:
        from datatunerx_tpu.ops.paged_attention import blocks_for_depth

        # spec engines reserve the verify-k write overshoot (spec_k + 1
        # tokens) on top of the request's own depth, capped at the block
        # table's width — see blocks_for_depth for the rationale
        return self._allocator.alloc(blocks_for_depth(
            depth, self.block_size, overshoot=self._spec_overshoot,
            cap_depth=self.max_seq_len))

    def _table_row(self, blocks: List[int]) -> jnp.ndarray:
        row = np.full((self.blocks_per_slot,), -1, np.int32)
        row[: len(blocks)] = blocks
        return jnp.asarray(row)

    def _trace(self, *event):
        self.sched_trace.append(event)

    def _complete(self, req: Request, error: Optional[str] = None):
        """Finish a request AND flush its buffered observability: one
        TTFT/TPOT observe pair per request (never per token) and, with
        tracing on, the request's span timeline into the trace ring."""
        n = len(req.tokens)
        if req.first_token_ts is not None:
            # exemplar only when tracing: the trace id is then resolvable at
            # GET /debug/trace/<id>, and the tracing-off observe stays the
            # bare-arithmetic path (token-parity test's no-overhead contract)
            tid = req.trace_id if self.tracing else None
            self._h_ttft.observe((req.first_token_ts - req.t_submit) * 1e3,
                                 trace_id=tid)
            if req.last_token_ts is not None and n > 1:
                self._h_tpot.observe(
                    (req.last_token_ts - req.first_token_ts) / (n - 1) * 1e3,
                    trace_id=tid)
        if self.tenants is not None and getattr(req, "tenant", ""):
            self._tenant_count(req.tenant, "tokens_out", n)
        if self.tracing:
            span = build_request_span(
                req.trace_id, req.t_submit, req.timeline,
                req.first_token_ts, req.last_token_ts, n,
                req.wall_submit_ms, error=error,
                attrs={"adapter": req.adapter_name or req.adapter,
                       "prompt_len": len(req.prompt_ids)},
            )
            self.trace_store.add(span)
        req.finish(error=error)

    def _take_waiting(self) -> Optional[Request]:
        if self._waiting_front:
            return self._waiting_front.popleft()
        try:
            return self._waiting.get_nowait()
        except queue.Empty:
            return None

    def _requeue_front(self, reqs: List[Request]):
        """Restore requests to the FRONT of the wait order, preserving
        their relative (older-first) order."""
        for req in reversed(reqs):
            self._waiting_front.appendleft(req)

    def _admit_waiting(self):
        # requests whose adapter is mid-load this pass: parked aside so
        # YOUNGER requests can fill other slots while the checkpoint reads
        # (their pool slot is already reserved by the load, so bypass
        # cannot starve them — they re-admit at their FIFO position)
        parked: List[Request] = []
        for slot in range(self.slots):
            if self._slot_req[slot] is not None:
                continue
            while True:
                req = self._take_waiting()
                if req is None:
                    self._requeue_front(parked)
                    return
                if (self._preempted
                        and self._preempted[0]["req"].seq < req.seq):
                    # strict FIFO across parked populations: a preempted
                    # session older than this cold request resumes first —
                    # admitting the younger one would hand it the very
                    # blocks the parked head is waiting for
                    self._requeue_front(parked + [req])
                    return
                try:
                    ok = self._admit(req, slot)
                except Exception as e:  # noqa: BLE001 — fail request, not loop
                    self._complete(req, error=str(e))
                    continue  # try the next request for this slot
                if ok:
                    break
                if self._admit_wait_reason == "adapter":
                    parked.append(req)
                    continue
                # KV blocks exhausted: the FIFO head waits for freed blocks
                # (younger requests must not starve it by sneaking in —
                # they'd consume the very blocks it needs)
                self._requeue_front(parked + [req])
                return
        self._requeue_front(parked)

    def _prefill_tick(self):
        """Spend AT MOST ``prefill_token_budget`` prompt tokens on pending
        chunked prefills (admission order), then yield back to decode. The
        bound is hard: the last chunk of a tick is clamped to the remaining
        budget (all three operands — prefill_chunk, the budget, and plen,
        whose kept-prompt cap prepare_prompt floors to a bucket multiple — are
        bucket multiples, so the clamp never produces an off-bucket program).
        A budget of 0 prefills every pending prompt to completion."""
        if not self._pending:
            return
        budget = self.prefill_token_budget or float("inf")
        spent = 0
        for slot in list(self._pending.keys()):
            st = self._pending[slot]
            req = st["req"]
            while spent < budget:
                c = min(self.prefill_chunk, st["plen"] - st["done"],
                        budget - spent)
                lo = st["done"]
                t0 = time.perf_counter()
                try:
                    with jax.profiler.TraceAnnotation("dtx_engine_prefill_chunk"):
                        logits, self._cache = self._prefill_chunk_fn(
                            self.params, self._lora_arg(), self._cache,
                            jnp.asarray(slot, jnp.int32),
                            jnp.asarray([st["ids"][lo:lo + c]], jnp.int32),
                            jnp.asarray([st["mask"][lo:lo + c]], jnp.int32),
                            jnp.asarray([st["positions"][lo:lo + c]], jnp.int32),
                            jnp.asarray(st["adapter"], jnp.int32),
                            chunk_len=c,
                        )
                except Exception as e:  # noqa: BLE001 — fail request, not loop
                    self._release_slot(slot)
                    self._complete(req, error=str(e))
                    break
                # wall time as the scheduler sees it: on a synchronous
                # backend this is the chunk's execution; under async
                # dispatch it is dispatch + queue drain — no extra sync is
                # added here to make it "exact" (the budget bound, not this
                # number, is the scheduling contract)
                self._h_prefill_chunk.observe(
                    (time.perf_counter() - t0) * 1e3)
                st["done"] += c
                spent += c
                self._trace("prefill", slot, c)
                if self.tracing:
                    req.mark("prefill", slot=slot, tokens=c)
                if st["done"] >= st["plen"]:
                    self._finish_prefill(slot, st, logits)
                    break
            if spent >= budget:
                break

    def _finish_prefill(self, slot: int, st: dict, row_logits):
        del self._pending[slot]
        req = st["req"]
        # COW suffix prefills start at a shared-prefix base cursor; the
        # decode extent is measured from the FINAL cursor, exactly like
        # the dense extension path's clamp
        cursor = st.get("base", 0) + st["plen"]
        max_new = max(1, min(st["max_new"], self.max_seq_len - cursor))
        (self._logits, self._pos, self._remaining, self._active, self._temps,
         self._top_ps, self._stops, self._adapter_idx, self._rng) = \
            self._activate(
                self._logits, self._pos, self._remaining, self._active,
                self._temps, self._top_ps, self._stops, self._adapter_idx,
                self._rng, jnp.asarray(slot, jnp.int32), row_logits,
                *self._arm_args(req, st["n_prompt"], max_new),
            )
        self._decode_ready[slot] = True
        if not st.get("base") and st.get("key") is not None:
            # suffix extensions already counted as "extend" at admission;
            # imported mid-prefill tails (key None) are not cold prefills
            self.prefill_stats["full"] += 1
        if st.get("key") is not None:
            self._slot_key[slot] = (st["key"], cursor)
        if self._prefix is not None and st.get("key") is not None:
            if self.cow:
                # publish refcounted blocks — no dense-row materialisation
                self._cow_store(slot, st["key"], cursor, row_logits)
            else:
                # export the slot's blocks as a dense row so later prompts
                # can reuse/extend this prefix exactly like in dense mode —
                # TRIMMED to the live cursor (PR 12 row_trim math inside
                # paged_extract_row), so short prefixes stop paying a full
                # max_seq_len gather per insert
                row = self._extract(self._cache,
                                    jnp.asarray(slot, jnp.int32),
                                    jnp.asarray(cursor, jnp.int32),
                                    width=cursor)
                self._prefix.put(st["key"], {"cache": row,
                                             "logits": row_logits,
                                             "cursor": cursor})
        self._trace("activate", slot)
        if self.tracing:
            req.mark("activate", slot=slot)

    # ------------------------------------------------- KV migration fabric
    def export_sessions(self, slots: Optional[Sequence[int]] = None,
                        wire_quant: Optional[str] = None,
                        timeout_s: float = 30.0,
                        include_prefill: bool = False) -> dict:
        """Serialize every in-flight decode session (or just ``slots``)
        into portable payloads (serving/migration.py wire format) AND
        terminate the source requests with the migrated marker — their
        streams end, and the gateway splices the imported continuation.

        Runs on the scheduler thread (state owner); this call just queues
        the command and waits. Returns {"sessions": [...], "skipped":
        [{"slot", "reason"}]} — slots mid-chunked-prefill are skipped
        (their KV is incomplete; they finish in place on the draining
        replica, the counted fallback).

        ``include_prefill=True`` ships mid-chunked-prefill slots too
        (disaggregated handoff): the payload carries the blocks written so
        far plus a ``pending`` document with the remaining prompt tail, and
        the importer resumes chunked prefill where the source stopped."""
        return self._mig_call({"kind": "export",
                               "slots": (None if slots is None
                                         else [int(s) for s in slots]),
                               "wire": wire_quant,
                               "prefill": bool(include_prefill)}, timeout_s)

    def import_session(self, payload: dict, timeout_s: float = 30.0,
                       wait_s: float = 10.0) -> dict:
        """Admit an exported session: allocate blocks, scatter the KV row
        back in (``paged_insert_row`` via the same jitted insert admission
        uses), restore the decode state — including the slot's live PRNG
        key, so greedy AND fixed-seed sampled resumption are token-exact —
        and resume decode.

        Transient shortages (no free slot, KV blocks exhausted, adapter
        still loading) PARK the import and retry each scheduler tick for
        up to ``wait_s`` — a busy target admits the migrating session as
        soon as capacity frees, ahead of its cold FIFO queue — then refuse.
        Raises ValueError on refusals (including permanent ones: unknown
        adapter, incompatible model) and RuntimeError on engine faults.
        The returned meta carries ``"_request"`` (the live Request handle
        for ``resume_stream``) and ``text_so_far`` (the detokenized
        migrated tail)."""
        return self._mig_call(
            {"kind": "import", "payload": payload,
             "deadline": time.monotonic() + wait_s}, timeout_s)

    def hold_parked(self, max_sessions: int = 4, hold_s: float = 10.0,
                    timeout_s: float = 30.0) -> dict:
        """Phase 1 of a peer spill: lease up to ``max_sessions``
        preemption-parked payloads to the fleet coordinator. A held entry
        will not resume locally until the hold expires (or is released) —
        and, because the parked head still gates younger cold admissions,
        FIFO fairness holds while the coordinator re-homes it. Holds are
        time-bounded so a dead coordinator never wedges resumption.
        Returns {"sessions": [{"trace_id", "seq", "cursor", "remaining",
        "payload"}], "parked": n}."""
        return self._mig_call({"kind": "hold_parked",
                               "max_sessions": int(max_sessions),
                               "hold_s": float(hold_s)}, timeout_s)

    def drop_parked(self, trace_ids: Sequence[str],
                    timeout_s: float = 30.0) -> dict:
        """Phase 2 (success): the coordinator imported these parked
        sessions onto a peer — drop them here and terminate their source
        requests with the migrated marker so the gateway splices."""
        return self._mig_call({"kind": "drop_parked",
                               "trace_ids": [str(t) for t in trace_ids]},
                              timeout_s)

    def release_parked(self, trace_ids: Sequence[str],
                       timeout_s: float = 30.0) -> dict:
        """Phase 2 (failure): the peer refused — clear the hold so the
        sessions resume locally as if the spill was never attempted."""
        return self._mig_call({"kind": "release_parked",
                               "trace_ids": [str(t) for t in trace_ids]},
                              timeout_s)

    def export_prefix_entries(self, exclude: Optional[Sequence[str]] = None,
                              max_entries: int = 4,
                              wire_quant: Optional[str] = None,
                              timeout_s: float = 30.0) -> dict:
        """Serialize up to ``max_entries`` local prefix-cache entries
        (MRU first) as ``dtx-kv-prefix`` payloads for the fleet-shared
        prefix tier, skipping fingerprints in ``exclude`` (what the
        gateway directory already holds). Non-destructive: entries stay
        cached locally. Returns {"entries": [payload, ...]}."""
        return self._mig_call({"kind": "export_prefix",
                               "exclude": (set(exclude) if exclude
                                           else set()),
                               "max_entries": int(max_entries),
                               "wire": wire_quant}, timeout_s)

    def import_prefix_entry(self, payload: dict, timeout_s: float = 30.0,
                            wait_s: float = 5.0) -> dict:
        """Install a fleet-published prefix payload into the local
        ``_PrefixCache`` so the NEXT prompt sharing that prefix admits via
        the COW hit path with zero prefill chunks. Transient block
        shortages retry until ``wait_s``; permanent mismatches (model
        signature, unknown adapter) raise ValueError."""
        return self._mig_call(
            {"kind": "import_prefix", "payload": payload,
             "deadline": time.monotonic() + wait_s}, timeout_s)

    def resume_stream(self, req: Request):
        """Continuation deltas of an imported session: text BEYOND the
        migrated tail, streamed as decode produces it (the tail itself was
        already emitted to the client by the source replica)."""
        acc = list(req.tokens[: getattr(req, "resume_base", 0)])
        sent = (self.tokenizer.decode(acc, skip_special_tokens=True)
                if acc else "")
        while True:
            t = req.stream.get()
            if t is None:
                break
            acc.append(t)
            text = self.tokenizer.decode(acc, skip_special_tokens=True)
            if len(text) > len(sent) and not text.endswith("�"):
                yield text[len(sent):]
                sent = text
        if req.error:
            raise RuntimeError(req.error)

    def adapter_catalog(self) -> Dict[str, str]:
        """Registered adapter name → checkpoint path (dynamic pools only)
        — what a replacement replica needs to rebuild this replica's
        warm set."""
        if self.adapter_registry is None:
            return {}
        return {n: self.adapter_registry.describe(n)["checkpoint"]
                for n in self.adapter_registry.names()}

    def _mig_call(self, cmd: dict, timeout_s: float):
        if self._shutdown.is_set():
            raise RuntimeError("engine is shut down")
        cmd["_done"] = threading.Event()
        self._mig_q.put(cmd)
        self._wake.set()
        if not cmd["_done"].wait(timeout_s):
            raise TimeoutError(
                f"engine did not service session {cmd['kind']} within "
                f"{timeout_s}s")
        if cmd.get("_error"):
            if cmd.get("_refused"):
                raise ValueError(cmd["_error"])
            raise RuntimeError(cmd["_error"])
        return cmd["_result"]

    def _count_mig(self, kind: str, outcome: str):
        d = self.session_stats.setdefault(kind, {})
        d[outcome] = d.get(outcome, 0) + 1

    def _service_migrations(self):
        if not self._mig_retry and self._mig_q.empty():
            return
        pending, self._mig_retry = self._mig_retry, []
        while True:
            try:
                pending.append(self._mig_q.get_nowait())
            except queue.Empty:
                break
        for cmd in pending:
            try:
                if cmd["kind"] == "export":
                    cmd["_result"] = self._do_export(cmd)
                elif cmd["kind"] == "import":
                    cmd["_result"] = self._do_import(cmd)
                elif cmd["kind"] == "hold_parked":
                    cmd["_result"] = self._do_hold_parked(cmd)
                elif cmd["kind"] == "drop_parked":
                    cmd["_result"] = self._do_drop_parked(cmd)
                elif cmd["kind"] == "release_parked":
                    cmd["_result"] = self._do_release_parked(cmd)
                elif cmd["kind"] == "export_prefix":
                    cmd["_result"] = self._do_export_prefix(cmd)
                elif cmd["kind"] == "import_prefix":
                    cmd["_result"] = self._do_import_prefix(cmd)
                else:
                    raise ValueError(
                        f"unknown session command {cmd['kind']!r}")
            except _RetryLater as retry:
                if time.monotonic() < cmd.get("deadline", 0.0):
                    cmd["_retry_reason"] = str(retry)
                    self._mig_retry.append(cmd)
                    continue
                cmd["_error"] = str(retry)
                cmd["_refused"] = True
                self._count_mig(cmd["kind"], "refused")
            except (ValueError, KeyError) as e:
                cmd["_error"] = str(e)
                cmd["_refused"] = True
                self._count_mig(cmd["kind"], "refused")
            except Exception as e:  # noqa: BLE001 — fail the command, not the loop
                cmd["_error"] = str(e)
                cmd["_refused"] = False
                self._count_mig(cmd["kind"], "error")
            cmd["_done"].set()

    def _do_export(self, cmd: dict) -> dict:
        want = cmd.get("slots")
        sessions: List[dict] = []
        skipped: List[dict] = []
        for slot in range(self.slots):
            if want is not None and slot not in want:
                continue
            req = self._slot_req[slot]
            if req is None:
                if want is not None:
                    skipped.append({"slot": slot, "reason": "empty"})
                continue
            if not self._decode_ready[slot]:
                st = self._pending.get(slot)
                if cmd.get("prefill") and st is not None and self.paged:
                    # disaggregated handoff: ship the blocks written so
                    # far plus the remaining prompt tail — the importer
                    # resumes chunked prefill exactly where we stopped
                    try:
                        payload = self._export_prefill_slot(
                            slot, st, cmd.get("wire"))
                    except Exception as e:  # noqa: BLE001 — skip slot, keep rest
                        skipped.append({"slot": slot, "reason": str(e)})
                        self._count_mig("export", "error")
                        continue
                    sessions.append(payload)
                    self._count_mig("export", "ok_prefill")
                    self._trace("export_prefill", slot)
                    if self.tracing:
                        req.mark("export", slot=slot, prefill=True,
                                 done=st["done"])
                    self._release_slot(slot)
                    self._active = self._active.at[slot].set(False)
                    self._remaining = self._remaining.at[slot].set(0)
                    from datatunerx_tpu.serving.migration import (
                        MIGRATED_SESSION,
                    )

                    self._complete(
                        req,
                        error=f"{MIGRATED_SESSION}: prefill slot exported")
                    continue
                skipped.append({"slot": slot,
                                "reason": "prefill_in_progress"})
                self._count_mig("export", "skipped_prefill")
                continue
            try:
                # a spec-active slot first settles: its pending token's KV
                # is written and next-token logits materialize, so the
                # payload is the standard logits-form wire format any
                # replica (spec or not) can import; the importer re-primes
                # its own draft cache rather than shipping draft KV
                if self.spec is not None and self._spec_form[slot]:
                    self._spec_settle_slot(slot)
                payload = self._export_slot(slot, req, cmd.get("wire"))
            except Exception as e:  # noqa: BLE001 — skip the slot, keep the rest
                skipped.append({"slot": slot, "reason": str(e)})
                self._count_mig("export", "error")
                continue
            sessions.append(payload)
            self._count_mig("export", "ok")
            self._trace("export", slot)
            if self.tracing:
                req.mark("export", slot=slot, cursor=payload["cursor"])
            if self.prefix_keep_warm:
                # keep the session's prompt rows warm across the drain so
                # a later resume-on-peer (or a sibling tenant) gets a COW
                # hit instead of a cold prefill
                self._keep_warm(slot)
            self._release_slot(slot)
            # the slot is still ACTIVE on device — every other release
            # happens after the decode kernel deactivated it. Clear the
            # mask (and the token budget) NOW: an interleaved decode chunk
            # would otherwise keep sampling this slot and write a stale
            # token through the NEXT tenant's freshly-installed block
            # table while that tenant is still chunk-prefilling.
            self._active = self._active.at[slot].set(False)
            self._remaining = self._remaining.at[slot].set(0)
            from datatunerx_tpu.serving.migration import MIGRATED_SESSION

            self._complete(req, error=f"{MIGRATED_SESSION}: slot exported")
        if want is None and self._preempted:
            # preemption-parked sessions are in flight too — a drain that
            # missed them would strand their clients. Their payloads
            # already exist (raw numpy bodies): re-encode for the wire,
            # terminate with the migrated marker so the gateway splices.
            from datatunerx_tpu.serving.migration import (
                MIGRATED_SESSION,
                encode_payload,
            )

            # entries leased to the spill coordinator stay parked: the
            # coordinator (or lease expiry) is their single owner — a
            # drain exporting them too would fork the session onto two
            # replicas at once
            now = time.monotonic()
            parked = [e for e in self._preempted
                      if e.get("hold_until", 0.0) <= now]
            self._preempted = [e for e in self._preempted
                               if e.get("hold_until", 0.0) > now]
            for entry in parked:
                req = entry["req"]
                sessions.append(encode_payload(entry["payload"]))
                self._count_mig("export", "ok")
                self._trace("export_parked", req.seq)
                if self.tracing:
                    req.mark("export", parked=True)
                self._complete(
                    req, error=f"{MIGRATED_SESSION}: parked session exported")
        return {"sessions": sessions, "skipped": skipped}

    def _export_slot(self, slot: int, req: Request,
                     wire: Optional[str], b64: bool = True) -> dict:
        from datatunerx_tpu.serving import migration as mig

        # the migration path's designed sync point: the slot's scalar
        # decode state crosses to host once per exported session
        cursor, pos, remaining, rng, logits = jax.device_get(  # dtxlint: disable=DTX001
            (self._cache["len"][slot], self._pos[slot],
             self._remaining[slot], self._rng[slot], self._logits[slot]))
        if self.paged:
            # gather only the live prefix's blocks (bucket-rounded so the
            # static-width program count stays bounded) — the wire pays
            # cursor columns, not a max_seq_len row
            w = min(-(-max(1, int(cursor)) // DECODE_BUCKET) * DECODE_BUCKET,  # dtxlint: disable=DTX001 — cursor is host (device_get above)
                    self.max_seq_len)
            row = self._extract(self._cache, jnp.asarray(slot, jnp.int32),
                                jnp.asarray(cursor, jnp.int32), width=w)
        else:
            row = {"k": self._cache["k"][:, slot:slot + 1],
                   "v": self._cache["v"][:, slot:slot + 1],
                   "pos": self._cache["pos"][slot:slot + 1],
                   "len": jnp.asarray(cursor, jnp.int32)}
            if "k_scale" in self._cache:
                row["k_scale"] = self._cache["k_scale"][:, slot:slot + 1]
                row["v_scale"] = self._cache["v_scale"][:, slot:slot + 1]
        payload = mig.build_payload(
            self.cfg, self.kv_quant,
            request={"trace_id": req.trace_id,
                     "adapter": req.adapter_name,
                     "prompt_ids": list(req.prompt_ids),
                     "tokens": list(req.tokens),
                     "max_new_tokens": req.max_new_tokens,
                     "temperature": req.temperature, "top_p": req.top_p,
                     "seed": req.seed, "stop_ids": list(req.stop_ids)},
            row=row, cursor=cursor, pos=pos, remaining=remaining,
            rng=rng, logits=logits, wire=wire, b64=b64)
        # learned spec-controller state rides the payload as plain JSON
        # (encode/normalize pass unknown keys through untouched): the
        # destination's re-prime rebuilds the draft KV, but without this
        # the controller restarts cold — acceptance EMAs and learned tree
        # widths would relearn from scratch after every migration
        if self.spec is not None:
            payload["spec"] = self.spec_ctrl.export_slot_state(slot)
        return payload

    def _export_prefill_slot(self, slot: int, st: dict,
                             wire: Optional[str]) -> dict:
        """Serialize a mid-chunked-prefill slot: the KV written so far
        (``base + done`` lanes) plus a ``pending`` document carrying the
        un-prefilled prompt tail. No decode state exists yet — rng/logits
        are placeholders; the importer's ``_finish_prefill`` arms the slot
        from ``req.seed`` exactly as an undisturbed in-place prefill
        would, so the handoff is token-exact by construction."""
        from datatunerx_tpu.serving import migration as mig

        req = st["req"]
        cursor = int(st.get("base", 0)) + int(st["done"])  # dtxlint: disable=DTX001 — pending-doc fields are host ints
        w = min(-(-max(1, cursor) // DECODE_BUCKET) * DECODE_BUCKET,
                self.max_seq_len)
        row = self._extract(self._cache, jnp.asarray(slot, jnp.int32),
                            jnp.asarray(cursor, jnp.int32), width=w)
        payload = mig.build_payload(
            self.cfg, self.kv_quant,
            request={"trace_id": req.trace_id,
                     "adapter": req.adapter_name,
                     "prompt_ids": list(req.prompt_ids),
                     "tokens": list(req.tokens),
                     "max_new_tokens": req.max_new_tokens,
                     "temperature": req.temperature, "top_p": req.top_p,
                     "seed": req.seed, "stop_ids": list(req.stop_ids)},
            row=row, cursor=cursor, pos=st["n_prompt"],
            remaining=st["max_new"], rng=np.zeros(2, np.uint32),
            logits=np.zeros((self.cfg.vocab_size,), np.float32),
            wire=wire, b64=True)
        done = int(st["done"])  # dtxlint: disable=DTX001 — pending-doc fields are host ints
        payload["pending"] = {
            "ids": [int(t) for t in st["ids"][done:]],  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "mask": [int(m) for m in st["mask"][done:]],  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "positions": [int(p) for p in st["positions"][done:]],  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "n_prompt": int(st["n_prompt"]),  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "max_new": int(st["max_new"]),  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "base": int(st.get("base", 0)),  # dtxlint: disable=DTX001 — pending-doc fields are host ints
            "done": done,
        }
        return payload

    def _do_import(self, cmd: dict) -> dict:
        from datatunerx_tpu.serving import migration as mig

        payload = mig.normalize_payload(cmd["payload"], self.cfg)
        cursor = payload["cursor"]
        pos_val = payload["pos"]
        W = self.max_seq_len
        if cursor >= W:
            raise ValueError(
                f"session depth {cursor} exceeds this replica's context {W}")
        remaining = max(1, min(payload["remaining"], W - cursor))
        slot = next((i for i in range(self.slots)
                     if self._slot_req[i] is None), None)
        if slot is None:
            raise _RetryLater(
                f"no free cache slot to import into ({self.slots} busy)")
        name = payload["adapter"]
        idx = 0
        pinned = False
        if name:
            if self.adapter_registry is not None:
                # hit/miss stats latch across retry ticks, like a
                # readmission retry at _admit
                first_lookup = not cmd.get("_adapter_seen", False)
                cmd["_adapter_seen"] = True
                try:
                    acquired = self.adapter_registry.acquire(
                        name, count_hit=first_lookup)
                except KeyError:
                    raise ValueError(
                        f"unknown adapter {name!r} on this replica")
                if acquired is None:
                    # mid-load (or pool pinned): retry next tick until the
                    # command's deadline — the import itself kicked the
                    # load-on-miss, same as admission would
                    loading = self.adapter_registry.describe(
                        name).get("loading", False)
                    raise _RetryLater(
                        f"adapter {name!r} "
                        + ("still loading" if loading
                           else "pool exhausted (all slots pinned)"))
                idx, pinned = acquired, True
            elif name in self._static_adapter_ids:
                idx = self._static_adapter_ids[name]
            else:
                raise ValueError(f"unknown adapter {name!r} on this replica")
        pending = payload.get("pending")
        if pending is not None:
            try:
                return self._import_prefill_tail(payload, pending, slot,
                                                 name, idx, pinned, cursor)
            except Exception:
                if pinned:
                    self.adapter_registry.release(name)
                raise
        blocks: Optional[List[int]] = None
        try:
            if self.paged:
                # overcommit engines import lazily too: the grower extends
                # the table as the resumed decode advances
                depth = self._reserve_depth(cursor, remaining)
                blocks = self._alloc_blocks(depth)
                if blocks is None:
                    raise _RetryLater(
                        "kv blocks exhausted "
                        f"(need {-(-depth // self.block_size)}"
                        f", free {self._allocator.free_count})")
            row = mig.unpack_kv_row(payload["kv"], full_width=W,
                                    quantize=self.kv_quant)
            row_logits = mig.unpack_logits(payload, self.cfg.vocab_size)
            req = Request(
                payload["prompt_ids"], payload["max_new_tokens"],
                payload["temperature"], payload["top_p"],
                payload["seed"], payload["stop_ids"],
                idx, adapter_name=name,
                trace_id=(payload["trace_id"]
                          or f"dtx-{uuid.uuid4().hex[:16]}"))
            req.tokens = payload["tokens"]
            req.resume_base = len(req.tokens)
            if self.spec is not None:
                # re-prime contract: the wire carries no draft-cache state;
                # the slot joins speculative decoding after its draft row
                # is re-prefilled from the payload's prompt + tail (the
                # scheduler does this before the slot's first spec step —
                # priming affects acceptance only, never output exactness)
                from datatunerx_tpu.utils.decoding import prepare_prompt

                p_ids, _, _, p_plen, p_n, _, _ = prepare_prompt(
                    payload["prompt_ids"], self.tokenizer.eos_token_id,
                    self.max_seq_len, payload["max_new_tokens"])
                req.spec_prime_ids = p_ids[p_plen - p_n:]
                # warm the controller from the source's learned state
                # (acceptance EMAs, learned per-depth widths): re-prime
                # rebuilds the draft KV but must not reset what the source
                # already learned about this session's acceptance
                self.spec_ctrl.import_slot_state(slot, payload.get("spec"))
            if self.paged:
                (self._cache, self._logits, self._pos, self._remaining,
                 self._active, self._temps, self._top_ps, self._stops,
                 self._adapter_idx, self._rng) = self._insert_paged(
                    self._cache, self._logits, self._pos, self._remaining,
                    self._active, self._temps, self._top_ps, self._stops,
                    self._adapter_idx, self._rng,
                    jnp.asarray(slot, jnp.int32), self._table_row(blocks),
                    row, row_logits, jnp.asarray(cursor, jnp.int32),
                    *self._arm_args(req, pos_val, remaining),
                )
            else:
                (self._cache, self._logits, self._pos, self._remaining,
                 self._active, self._temps, self._top_ps, self._stops,
                 self._adapter_idx, self._rng) = self._insert(
                    self._cache, self._logits, self._pos, self._remaining,
                    self._active, self._temps, self._top_ps, self._stops,
                    self._adapter_idx, self._rng,
                    jnp.asarray(slot, jnp.int32), row, row_logits,
                    jnp.asarray(cursor, jnp.int32),
                    *self._arm_args(req, pos_val, remaining),
                )
            # token-exact resume: replace the seed-derived key the insert
            # armed with the SOURCE slot's live rng stream
            self._rng = self._rng.at[slot].set(
                jnp.asarray(payload["rng"], jnp.uint32))
        except Exception:
            if blocks:
                self._allocator.free(blocks)
            if pinned:
                self.adapter_registry.release(name)
            raise
        if pinned:
            self._slot_adapter[slot] = name
        self._slot_blocks[slot] = blocks or []
        self._slot_req[slot] = req
        self._decode_ready[slot] = True
        if self.paged:
            self._slot_demand[slot] = self._eager_demand(cursor, remaining)
        self._note_admitted(slot)
        self._count_mig("import", "ok")
        self._trace("import", slot, cursor)
        if self.tracing:
            req.mark("import", slot=slot, cursor=cursor, adapter=name,
                     tail_tokens=req.resume_base)
        text = (self.tokenizer.decode(req.tokens, skip_special_tokens=True)
                if req.tokens else "")
        return {"session": req.trace_id, "slot": slot,
                "tokens": req.resume_base, "cursor": cursor,
                "remaining": remaining, "adapter": name,
                "text_so_far": text, "_request": req}

    def _import_prefill_tail(self, payload: dict, pending: dict, slot: int,
                             name: str, idx: int, pinned: bool,
                             cursor: int) -> dict:
        """Admit a mid-chunked-prefill export: scatter the KV written so
        far into fresh blocks, then register the remaining prompt tail as
        a normal ``_pending`` chunked prefill (``key`` None — an imported
        tail is not a cold prefill and never publishes a prefix entry).
        ``_finish_prefill`` then arms decode from ``req.seed`` exactly as
        the source replica would have, so the handoff is token-exact."""
        from datatunerx_tpu.ops.paged_attention import paged_insert_row
        from datatunerx_tpu.serving import migration as mig

        if not self.paged:
            raise ValueError("mid-prefill import requires a paged engine")
        ids = [int(t) for t in pending["ids"]]  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        mask = [int(m) for m in pending["mask"]]  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        positions = [int(p) for p in pending["positions"]]  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        final = cursor + len(ids)
        W = self.max_seq_len
        if final >= W:
            raise ValueError(
                f"prefill depth {final} exceeds this replica's context {W}")
        max_new = max(1, int(pending["max_new"]))  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        blocks = self._alloc_blocks(self._reserve_depth(final, max_new))
        if blocks is None:
            raise _RetryLater(
                "kv blocks exhausted for mid-prefill import "
                f"(free {self._allocator.free_count})")
        try:
            row = mig.unpack_kv_row(payload["kv"], full_width=W,
                                    quantize=self.kv_quant)
            req = Request(
                payload["prompt_ids"], payload["max_new_tokens"],
                payload["temperature"], payload["top_p"],
                payload["seed"], payload["stop_ids"],
                idx, adapter_name=name,
                trace_id=(payload["trace_id"]
                          or f"dtx-{uuid.uuid4().hex[:16]}"))
            req.tokens = payload["tokens"]
            req.resume_base = len(req.tokens)
            if self.spec is not None:
                from datatunerx_tpu.utils.decoding import prepare_prompt

                p_ids, _, _, p_plen, p_n, _, _ = prepare_prompt(
                    payload["prompt_ids"], self.tokenizer.eos_token_id,
                    self.max_seq_len, payload["max_new_tokens"])
                req.spec_prime_ids = p_ids[p_plen - p_n:]
                self.spec_ctrl.import_slot_state(slot, payload.get("spec"))
            # the row's unwritten tail is POS_SENTINEL-padded to full
            # width, so the scatter doubles as the recycled-block scrub
            self._cache = paged_insert_row(
                self._cache, slot, self._table_row(blocks), row)
            self._cache["len"] = self._cache["len"].at[slot].set(cursor)
        except Exception:
            self._allocator.free(blocks)
            raise
        if pinned:
            self._slot_adapter[slot] = name
        self._slot_blocks[slot] = blocks
        self._slot_req[slot] = req
        self._decode_ready[slot] = False
        self._slot_demand[slot] = self._eager_demand(final, max_new)
        self._pending[slot] = {
            "req": req, "ids": ids, "mask": mask, "positions": positions,
            "plen": len(ids), "n_prompt": int(pending["n_prompt"]),  # dtxlint: disable=DTX001 — wire payloads carry host scalars
            "max_new": max_new, "adapter": req.adapter, "done": 0,
            "base": cursor, "key": None,
        }
        self._note_admitted(slot)
        self._count_mig("import", "ok_prefill")
        self._trace("import_prefill", slot, cursor)
        if self.tracing:
            req.mark("import", slot=slot, cursor=cursor, adapter=name,
                     prefill=True, tail=len(ids))
        text = (self.tokenizer.decode(req.tokens, skip_special_tokens=True)
                if req.tokens else "")
        return {"session": req.trace_id, "slot": slot,
                "tokens": req.resume_base, "cursor": cursor,
                "remaining": max_new, "adapter": name,
                "text_so_far": text, "_request": req, "prefill": True}

    # ------------------------------------------------- fleet spill (parked)
    def _do_hold_parked(self, cmd: dict) -> dict:
        from datatunerx_tpu.serving.migration import encode_payload

        now = time.monotonic()
        hold_until = now + float(cmd.get("hold_s", 10.0))  # dtxlint: disable=DTX001 — mig-command args are host scalars
        limit = int(cmd.get("max_sessions", 4))  # dtxlint: disable=DTX001 — mig-command args are host scalars
        out = []
        for entry in self._preempted:
            if len(out) >= limit:
                break
            if entry.get("hold_until", 0.0) > now:
                continue  # already leased
            entry["hold_until"] = hold_until
            payload = entry["payload"]
            out.append({"trace_id": entry["req"].trace_id,
                        "seq": entry["req"].seq,
                        "cursor": int(payload["cursor"]),  # dtxlint: disable=DTX001 — parked payloads carry host scalars
                        "remaining": int(payload["remaining"]),  # dtxlint: disable=DTX001 — parked payloads carry host scalars
                        "payload": encode_payload(payload)})
        return {"sessions": out, "parked": len(self._preempted)}

    def _do_drop_parked(self, cmd: dict) -> dict:
        from datatunerx_tpu.serving.migration import MIGRATED_SESSION

        want = set(cmd.get("trace_ids") or [])
        keep, dropped = [], 0
        for entry in self._preempted:
            req = entry["req"]
            if req.trace_id in want:
                dropped += 1
                self._count_preempt("spilled")
                self._trace("spill", req.seq)
                if self.tracing:
                    req.mark("spill")
                self._complete(
                    req, error=f"{MIGRATED_SESSION}: parked session spilled")
            else:
                keep.append(entry)
        self._preempted = keep
        return {"dropped": dropped}

    def _do_release_parked(self, cmd: dict) -> dict:
        want = set(cmd.get("trace_ids") or [])
        released = 0
        for entry in self._preempted:
            if entry["req"].trace_id in want and entry.pop(
                    "hold_until", None) is not None:
                released += 1
        return {"released": released}

    # ------------------------------------------------- fleet prefix tier
    def _adapter_akey_name(self, akey) -> Optional[str]:
        """Cache-key adapter identity → fleet-wide NAME (dynamic pools key
        by name already; static stacks key by index). None = unmappable."""
        if isinstance(akey, str):
            return akey
        if akey == 0:
            return ""
        for n, idx in self._static_adapter_ids.items():
            if idx == akey:
                return n
        return None

    def _mount_entry_row(self, ent: dict, cursor: int):
        """Gather a COW block entry into a dense row by temporarily
        installing its blocks on a FREE slot's table (nothing reads an
        unoccupied slot's table, and it is restored before returning)."""
        slot = next((i for i in range(self.slots)
                     if self._slot_req[i] is None), None)
        if slot is None:
            raise _RetryLater("no free slot to stage a prefix export")
        w = min(-(-max(1, cursor) // DECODE_BUCKET) * DECODE_BUCKET,
                self.max_seq_len)
        saved = self._cache["block_tables"][slot]
        try:
            self._cache["block_tables"] = self._cache["block_tables"].at[
                slot].set(self._table_row(ent["blocks"]))
            return self._extract(self._cache, jnp.asarray(slot, jnp.int32),
                                 jnp.asarray(cursor, jnp.int32), width=w)
        finally:
            self._cache["block_tables"] = \
                self._cache["block_tables"].at[slot].set(saved)

    def _do_export_prefix(self, cmd: dict) -> dict:
        from datatunerx_tpu.serving import migration as mig

        if self._prefix is None:
            return {"entries": []}
        exclude = cmd.get("exclude") or set()
        limit = int(cmd.get("max_entries", 4))  # dtxlint: disable=DTX001 — mig-command args are host scalars
        wire = cmd.get("wire")
        entries: List[dict] = []
        for key, ent in self._prefix.snapshot_entries():
            if len(entries) >= limit:
                break
            ptoks, akey = key
            name = self._adapter_akey_name(akey)
            if name is None:
                continue
            fp = mig.prefix_fingerprint(name, ptoks)
            if fp in exclude:
                continue
            cursor = int(ent["cursor"])  # dtxlint: disable=DTX001 — prefix entries store host cursors
            try:
                if ent.get("blocks") is not None:
                    row = self._mount_entry_row(ent, cursor)
                else:
                    row = ent["cache"]
                entries.append({
                    "kind": mig.PREFIX_KIND,
                    "version": mig.PAYLOAD_VERSION,
                    "fingerprint": fp,
                    "adapter": name,
                    "prompt_ids": [int(t) for t in ptoks],  # dtxlint: disable=DTX001 — prefix entries store host cursors
                    "cursor": cursor,
                    "no_reuse": bool(ent.get("no_reuse", False)),
                    "logits": (None if ent.get("logits") is None
                               else mig.pack_logits(ent["logits"])),
                    "kv": mig.pack_kv_row(row, cursor, wire),
                    "model_sig": mig.model_signature(self.cfg,
                                                     self.kv_quant),
                })
                self._count_mig("export_prefix", "ok")
            except Exception:  # noqa: BLE001 — publish is best-effort
                self._count_mig("export_prefix", "error")
                continue
        return {"entries": entries}

    def _do_import_prefix(self, cmd: dict) -> dict:
        from datatunerx_tpu.ops.paged_attention import (
            paged_insert_row,
            row_trim,
        )
        from datatunerx_tpu.serving import migration as mig

        if self._prefix is None:
            raise ValueError("prefix cache disabled on this replica")
        payload = cmd["payload"]
        mig.check_prefix_signature(payload, self.cfg)
        name = payload.get("adapter") or ""
        if not name:
            akey = "" if self.adapter_registry is not None else 0
        elif self.adapter_registry is not None:
            if name not in self.adapter_registry.names():
                raise ValueError(f"unknown adapter {name!r} on this replica")
            akey = name
        elif name in self._static_adapter_ids:
            akey = self._static_adapter_ids[name]
        else:
            raise ValueError(f"unknown adapter {name!r} on this replica")
        ptoks = tuple(int(t) for t in payload["prompt_ids"])  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        key = (ptoks, akey)
        if self._prefix.get(key) is not None:
            return {"imported": False, "reason": "present"}
        cursor = int(payload["cursor"])  # dtxlint: disable=DTX001 — wire payloads carry host scalars
        if not 0 < cursor < self.max_seq_len:
            raise ValueError(
                f"prefix depth {cursor} unusable in context "
                f"{self.max_seq_len}")
        row = mig.unpack_kv_row(payload["kv"], full_width=self.max_seq_len,
                                quantize=self.kv_quant)
        logits = (None if payload.get("logits") is None
                  else mig.unpack_logits(payload, self.cfg.vocab_size))
        no_reuse = bool(payload.get("no_reuse")) or logits is None
        if self.cow:
            full, rem = divmod(cursor, self.block_size)
            n_blocks = full + (1 if rem else 0)
            blocks = self._allocator.alloc(n_blocks)
            if blocks is None:
                raise _RetryLater(
                    f"kv blocks exhausted for prefix import "
                    f"(need {n_blocks}, free {self._allocator.free_count})")
            slot = next((i for i in range(self.slots)
                         if self._slot_req[i] is None), None)
            if slot is None:
                self._allocator.free(blocks)
                raise _RetryLater("no free slot to stage a prefix import")
            try:
                # the scatter installs the table on the free slot; restore
                # it right after — the ENTRY owns these blocks, not a slot
                saved = self._cache["block_tables"][slot]
                self._cache = paged_insert_row(
                    self._cache, slot, self._table_row(blocks), row)
                self._cache["block_tables"] = \
                    self._cache["block_tables"].at[slot].set(saved)
            except Exception:
                self._allocator.free(blocks)
                raise
            ent = {"blocks": blocks, "full": full, "rem": rem,
                   "cursor": cursor, "logits": logits}
        else:
            w = min(-(-max(1, cursor) // DECODE_BUCKET) * DECODE_BUCKET,
                    self.max_seq_len)
            ent = {"cache": row_trim(row, w), "logits": logits,
                   "cursor": cursor}
        if no_reuse:
            ent["no_reuse"] = True
        self._prefix.put(key, ent)
        self._count_mig("import_prefix", "ok")
        self._trace("import_prefix", cursor)
        return {"imported": True, "cursor": cursor,
                "fingerprint": payload.get("fingerprint")}

    def _release_slot(self, slot: int, note_session: bool = True):
        self._slot_req[slot] = None
        self._pending.pop(slot, None)
        self._decode_ready[slot] = False
        self._slot_demand[slot] = 0
        self._slot_key[slot] = None
        if self.spec is not None:
            self._spec_form[slot] = False
            self._spec_primed[slot] = False
            self.spec_ctrl.reset_slot(slot)
            # prune-on-release, like the slot acceptance EMAs: per-slot
            # tree-path series never outlive the tenant that produced them
            self._spec_tree_slot_path.pop(slot, None)
        name, self._slot_adapter[slot] = self._slot_adapter[slot], None
        if name is not None and self.adapter_registry is not None:
            self.adapter_registry.release(name)
        blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
        if blocks:
            if note_session:
                # tables only grow, so the count at release IS the
                # session's peak physical footprint (bench p50/p95 source);
                # preemptions pass False — the session isn't over
                self.kv_stats["session_blocks"].append(len(blocks))
            # clear the table FIRST: a masked decode write from this slot
            # must never land in a block the allocator has already re-issued
            self._cache["block_tables"] = \
                self._cache["block_tables"].at[slot].set(-1)
            self._allocator.free(blocks)

    # --------------------------------------------- overcommit: grow/preempt
    def _grow_tick(self):
        """On-demand block growth, run between prefill and decode: keep
        every decode-ready slot's table covering the lanes the next tick
        can write (cursor + one chunk/verify advance + the spec write
        overshoot). A slot the pool cannot serve — even after reclaiming
        prefix-cache entries and preempting younger sessions — parks
        ITSELF host-side, unless it is the oldest live session: the oldest
        is never preempted and always claims what reclamation frees, which
        is the forward-progress guarantee."""
        if not self.overcommit:
            return
        ready = [s for s in range(self.slots)
                 if self._decode_ready[s] and self._slot_req[s] is not None]
        if not ready:
            return
        # tiny [S]-int32 reads at the tick's designed sync point
        lens = np.asarray(self._cache["len"])  # dtxlint: disable=DTX001
        rem = np.asarray(self._remaining)  # dtxlint: disable=DTX001
        ready.sort(key=lambda s: self._slot_req[s].seq)
        for slot in ready:
            req = self._slot_req[slot]
            if req is None:
                continue  # preempted by an older slot's reclaim this pass
            advance = min(self._tick_advance, max(1, int(rem[slot])))  # dtxlint: disable=DTX001 — host numpy from this tick's sync point
            depth = min(int(lens[slot]) + advance + self._spec_overshoot,  # dtxlint: disable=DTX001 — host numpy from this tick's sync point
                        self.max_seq_len)
            need = (blocks_for_depth(depth, self.block_size)
                    - len(self._slot_blocks[slot]))
            while need > 0:
                got = self._allocator.alloc(need)
                if got is not None:
                    self._install_growth(slot, got)
                    break
                if self._reclaim_for(req):
                    continue
                if not self._is_oldest_live(req):
                    self._preempt_slot(slot)
                break

    def _is_oldest_live(self, req: Request) -> bool:
        seqs = [r.seq for r in self._slot_req if r is not None]
        return bool(seqs) and req.seq == min(seqs)

    def _reclaim_for(self, req: Request) -> bool:
        """Free blocks for ``req``'s growth, cheapest casualty first:
        (1) drop an LRU prefix-cache block entry (a performance tier, not
        a session), (2) preempt the youngest strictly-younger decode
        session (it parks host-side and resumes token-exactly), (3)
        un-admit the youngest strictly-younger chunk-prefilling request
        (incomplete KV cannot export — it re-queues cold). False = nothing
        strictly younger left to give."""
        if self._prefix is not None:
            ent = self._prefix.pop_lru_block_entry()
            if ent is not None:
                self._allocator.free(ent["blocks"])
                return True
        victims = [s for s in range(self.slots)
                   if self._decode_ready[s]
                   and self._slot_req[s] is not None
                   and self._slot_req[s].seq > req.seq]
        victims = self._tenant_filter_victims(req, victims, self._slot_req)
        if victims:
            self._preempt_slot(
                self._pick_victim(victims, self._slot_req))
            return True
        pend = [s for s in list(self._pending)
                if self._pending[s]["req"].seq > req.seq]
        pend = self._tenant_filter_victims(
            req, pend, {s: self._pending[s]["req"] for s in pend})
        if pend:
            self._unadmit_pending(
                self._pick_victim(
                    pend, {s: self._pending[s]["req"] for s in pend}))
            return True
        return False

    def _tenant_filter_victims(self, req: Request, slots, req_of):
        """Tenancy guard on the victim pool: a BULK-tier requester may
        never preempt a pinned-tier tenant's session — pinned tenants
        paid for isolation from throughput traffic. No directory (or a
        non-bulk requester) passes the pool through untouched, keeping
        the tenancy-off preemption order byte-identical."""
        if self.tenants is None:
            return slots
        if getattr(req, "tenant_tier", "standard") != "bulk":
            return slots
        return [s for s in slots
                if getattr(req_of[s], "tenant_tier", "standard") != "pinned"]

    def _pick_victim(self, slots, req_of):
        """Which victim pays: tenancy off → youngest (the pre-tenancy
        order, exactly). Tenancy on → lowest tier first (bulk gives way
        before standard before pinned), youngest within the tier."""
        if self.tenants is None:
            return max(slots, key=lambda s: req_of[s].seq)
        from datatunerx_tpu.tenancy.directory import TIER_RANK

        return min(slots, key=lambda s: (
            TIER_RANK.get(getattr(req_of[s], "tenant_tier", "standard"), 1),
            -req_of[s].seq))

    def _install_growth(self, slot: int, new_blocks: List[int]):
        blocks = self._slot_blocks[slot]
        blocks.extend(new_blocks)
        arr = jnp.asarray(new_blocks, jnp.int32)
        # scrub the recycled blocks' positions BEFORE the table reveals
        # them to attention (same contract as cold admission)
        self._cache["pos"] = self._cache["pos"].at[arr].set(POS_SENTINEL)
        self._cache["block_tables"] = self._cache["block_tables"].at[
            slot].set(self._table_row(blocks))
        self._trace("grow", slot, len(new_blocks))

    def _preempt_slot(self, slot: int):
        """Park a decode session host-side: settle (spec), export its
        dtx-kv-session payload (raw numpy bodies — no base64 for
        in-process parking), deactivate the slot ON DEVICE, and release
        everything it held. The Request object stays live (same stream
        queue, same done event): resume re-installs the KV into a fresh
        slot and keeps pushing tokens to the same consumer, so the client
        never observes the preemption — zero re-prefill, zero drop."""
        req = self._slot_req[slot]
        if self.spec is not None and self._spec_form[slot]:
            self._spec_settle_slot(slot)
        payload = self._export_slot(slot, req, None, b64=False)
        if self.prefix_keep_warm:
            # publish the session's prompt rows before freeing them: a
            # resume (here or on a peer) admits via a COW hit instead of
            # re-paying the prefix prefill
            self._keep_warm(slot)
        self._release_slot(slot, note_session=False)
        # the slot is still ACTIVE on device (only the decode kernel
        # deactivates slots itself) — clear the mask and budget NOW, or an
        # interleaved chunk would keep sampling it and write a stale token
        # through the next tenant's freshly-installed table
        self._active = self._active.at[slot].set(False)
        self._remaining = self._remaining.at[slot].set(0)
        self._preempted.append({"payload": payload, "req": req})
        self._preempted.sort(key=lambda e: e["req"].seq)
        self._count_preempt("exported")
        self._trace("preempt", slot, req.seq)
        if self.tracing:
            req.mark("preempt", slot=slot)

    def _unadmit_pending(self, slot: int):
        """Roll a chunk-prefilling admission back to the cold queue: its
        KV is incomplete so it cannot export; blocks and adapter pin are
        released and the request re-queues at its FIFO position (seq
        order). It re-prefills on readmission — the only preemption
        outcome that repays work, reachable only when nothing younger is
        decoding."""
        req = self._pending[slot]["req"]
        self._release_slot(slot, note_session=False)
        self._waiting_front = collections.deque(
            sorted([*self._waiting_front, req], key=lambda r: r.seq))
        self._count_preempt("requeued_prefill")
        self._trace("preempt_prefill", slot, req.seq)
        if self.tracing:
            req.mark("preempt", slot=slot, kind="prefill")

    def _resume_preempted_tick(self):
        """Re-admit preemption-parked sessions, oldest first, ahead of the
        cold queue (the admission gate keeps anything younger waiting, so
        strict FIFO fairness is preserved across the park). A head that
        cannot resume yet (no free slot / blocks / adapter mid-load) parks
        everything behind it until the next tick."""
        while self._preempted:
            entry = self._preempted[0]
            if entry.get("hold_until", 0.0) > time.monotonic():
                # leased to the fleet spill coordinator: hold local
                # resumption (and, via the admission gate, younger cold
                # admissions) until the spill lands or the lease expires
                return
            try:
                ok = self._resume_one(entry)
            except Exception as e:  # noqa: BLE001 — fail the session, not the loop
                self._preempted.pop(0)
                self._count_preempt("error")
                self._complete(entry["req"], error=str(e))
                continue
            if not ok:
                return
            self._preempted.pop(0)

    def _resume_one(self, entry: dict) -> bool:
        from datatunerx_tpu.serving import migration as mig

        req = entry["req"]
        payload = entry["payload"]
        slot = next((i for i in range(self.slots)
                     if self._slot_req[i] is None), None)
        if slot is None:
            return False
        name = req.adapter_name
        idx, pinned = 0, False
        if name:
            if self.adapter_registry is not None:
                acquired = self.adapter_registry.acquire(name,
                                                         count_hit=False)
                if acquired is None:
                    return False  # mid-load / pool pinned: retry next tick
                idx, pinned = acquired, True
            else:
                idx = self._static_adapter_ids.get(name, req.adapter)
        cursor = int(payload["cursor"])  # dtxlint: disable=DTX001 — parked payloads carry host scalars
        remaining = int(payload["remaining"])  # dtxlint: disable=DTX001 — parked payloads carry host scalars
        blocks = None
        try:
            blocks = self._alloc_blocks(
                self._reserve_depth(cursor, remaining))
            if blocks is None and self._prefix is not None:
                # prefix-cache entries are the cheapest reclaim here too
                ent = self._prefix.pop_lru_block_entry()
                if ent is not None:
                    self._allocator.free(ent["blocks"])
                    blocks = self._alloc_blocks(
                        self._reserve_depth(cursor, remaining))
            if blocks is None:
                if pinned:
                    self.adapter_registry.release(name)
                return False
            row = mig.unpack_kv_row(payload["kv"],
                                    full_width=self.max_seq_len,
                                    quantize=self.kv_quant)
            row_logits = mig.unpack_logits(payload, self.cfg.vocab_size)
            (self._cache, self._logits, self._pos, self._remaining,
             self._active, self._temps, self._top_ps, self._stops,
             self._adapter_idx, self._rng) = self._insert_paged(
                self._cache, self._logits, self._pos, self._remaining,
                self._active, self._temps, self._top_ps, self._stops,
                self._adapter_idx, self._rng,
                jnp.asarray(slot, jnp.int32), self._table_row(blocks),
                row, row_logits, jnp.asarray(cursor, jnp.int32),
                *self._arm_args(req, int(payload["pos"]), remaining),  # dtxlint: disable=DTX001 — parked payloads carry host scalars
            )
            # token-exact resume: restore the slot's LIVE rng stream in
            # place of the seed-derived key the insert armed
            self._rng = self._rng.at[slot].set(
                jnp.asarray(payload["rng"], jnp.uint32))
        except Exception:
            if blocks:
                self._allocator.free(blocks)
            if pinned:
                self.adapter_registry.release(name)
            raise
        req.adapter = idx
        if pinned:
            self._slot_adapter[slot] = name
        self._slot_blocks[slot] = blocks
        self._slot_req[slot] = req
        self._decode_ready[slot] = True
        self._slot_demand[slot] = self._eager_demand(cursor, remaining)
        self._note_admitted(slot)
        self._count_preempt("resumed")
        self._trace("resume", slot, cursor)
        if self.tracing:
            req.mark("resume", slot=slot, cursor=cursor)
        return True

    # ------------------------------------------------ speculative decoding
    def _spec_prime_slot(self, slot: int):
        """Prefill the slot's context (kept prompt + settled emitted tokens)
        through the DRAFT model into its per-slot draft cache row. Priming
        affects only acceptance rate — verification guarantees output
        exactness regardless — so an import re-primed from the payload's
        prompt is correct by construction."""
        req = self._slot_req[slot]
        ids = list(getattr(req, "spec_prime_ids", None) or [])
        if not ids:
            ids = list(req.prompt_ids)[-self.max_seq_len:] or \
                [self.tokenizer.eos_token_id or 0]
        toks = ids + list(req.tokens)
        n, W = len(toks), self.max_seq_len
        if n > W:
            # context can't be represented in the draft row: this slot
            # rides the plain path for its lifetime (no re-prime loop)
            self.spec_ctrl.force_off_slot(slot)
            self._spec_primed[slot] = True
            return
        padded = min(-(-n // DECODE_BUCKET) * DECODE_BUCKET, W)
        pad = padded - n
        eos = self.tokenizer.eos_token_id or 0
        sp = self.spec
        sp["dcache"] = sp["programs"].prime(
            sp["dparams"], sp["dcache"], jnp.asarray(slot, jnp.int32),
            jnp.asarray([[eos] * pad + toks], jnp.int32),
            jnp.asarray([[0] * pad + [1] * n], jnp.int32),
            jnp.asarray([[0] * pad + list(range(n))], jnp.int32),
            jnp.asarray(padded, jnp.int32))
        self._spec_primed[slot] = True
        self._trace("spec_prime", slot, n)

    def _spec_settle_slot(self, slot: int):
        """Write the slot's pending token through the target (one masked
        single-token forward) so the slot returns to the standard
        logits-form state — the KV-migration wire format's contract. Every
        other row's cursor is restored inside the program."""
        if self.spec is None or not self._spec_form[slot]:
            return
        onehot = np.zeros((self.slots,), bool)
        onehot[slot] = True
        sp = self.spec
        row_logits, self._cache, self._pos = sp["programs"].settle(
            self.params, self._lora_arg(), self._cache, self._spec_pending,
            self._pos, self._adapter_idx, jnp.asarray(onehot))
        self._logits = jnp.where(jnp.asarray(onehot)[:, None], row_logits,
                                 self._logits)
        self._spec_form[slot] = False
        self._trace("spec_settle", slot)

    def _batch_sample_mode(self) -> str:
        """Static per-batch sampling mode (bounded compiled variants):
        all-greedy batches verify/sample by argmax alone — no
        distributions, no full-vocab sort; top_p-free sampled batches use
        plain softmax; only genuinely filtering batches pay the exact
        sorted top-p path. Derived from host-side request params — no
        device sync."""
        live = [r for r in self._slot_req if r is not None]
        if all(r.temperature <= 0.0 for r in live):
            return "greedy"
        if any(r.top_p < 1.0 and r.temperature > 0.0 for r in live):
            return "topp"
        return "simple"

    def _epilogue_mode(self) -> str:
        """Sampling mode the fused epilogue runs this tick, or the "off"
        sentinel — the SINGLE compiled variant running the legacy argsort
        sampler, so --sampling_epilogue off traces byte-identical
        programs to a pre-epilogue build."""
        return ("off" if self.sampling_epilogue != "on"
                else self._batch_sample_mode())

    def _spec_decode_tick(self):
        """One speculative scheduler tick, replacing the plain decode chunk:
        (1) freshly-ready slots get their draft row primed and transition to
        pending form (their first token sampled exactly as the plain step
        would); (2) if the adaptive controller approves, ONE draft-propose /
        verify-k program emits up to k+1 tokens per drafting row with ragged
        per-row advance, otherwise the pending-form plain chunk program runs
        at identical per-token cost to the non-spec path. Returns
        ``(emitted [n, S] np, active [S] np)`` for the shared push/finish
        loop."""
        sp = self.spec
        progs = sp["programs"]
        out_rows = []

        fresh = [s for s in range(self.slots)
                 if self._decode_ready[s] and self._slot_req[s] is not None
                 and not self._spec_form[s]]
        if fresh:
            for slot in fresh:
                if not self._spec_primed[slot]:
                    self._spec_prime_slot(slot)
            fresh_mask = np.zeros((self.slots,), bool)
            fresh_mask[fresh] = True
            (enter_emitted, self._spec_pending, self._remaining,
             self._active, self._rng) = progs.enter(
                self._logits, self._spec_pending, self._remaining,
                self._active, self._rng, self._temps, self._top_ps,
                self._stops, jnp.asarray(fresh_mask),
                mode=self._epilogue_mode())
            for slot in fresh:
                self._spec_form[slot] = True
            # first-token emissions stream ahead of this tick's chunk
            out_rows.append(np.asarray(enter_emitted)[None, :])  # dtxlint: disable=DTX001

        # tiny [S] scalars at the tick's designed sync point: which rows are
        # worth drafting for (active, ≥2 budget left, acceptance healthy)
        active_prev = np.asarray(self._active)  # dtxlint: disable=DTX001
        rem_np = np.asarray(self._remaining)  # dtxlint: disable=DTX001
        spec_rows = np.zeros((self.slots,), bool)
        for s in range(self.slots):
            spec_rows[s] = bool(
                self._spec_form[s] and self._spec_primed[s]
                and active_prev[s] and rem_np[s] >= 2
                and self.spec_ctrl.slot_enabled(s))

        if spec_rows.any() and self.spec_ctrl.use_spec():
            plan = self.spec_ctrl.current_plan()
            # the verify math itself needs the true batch mode even when
            # the fused epilogue is off (acceptance is mode-dependent);
            # only the DRAW inside the program routes through the
            # epilogue, gated by SpecPrograms.epilogue
            mode = self._batch_sample_mode()
            margin = None
            if plan[0] == "tree":
                widths = plan[1]  # learned (or rectangular) per-depth widths
                k = len(widths)  # accepted path depth plays the chain k role
                with jax.profiler.TraceAnnotation("dtx_engine_spec_tree"):
                    (emitted, acc, self._cache, sp["dcache"],
                     self._spec_pending, self._pos, self._remaining,
                     self._active, self._rng, margin) = progs.tree_step(
                        self.params, sp["dparams"], self._lora_arg(),
                        self._cache, sp["dcache"], self._spec_pending,
                        self._pos, self._remaining, self._active,
                        self._rng, self._temps, self._top_ps, self._stops,
                        self._adapter_idx, jnp.asarray(spec_rows),
                        widths=widths, mode=mode)
                self.spec_stats["tree_steps"] += 1
            else:
                k = plan[1]
                with jax.profiler.TraceAnnotation("dtx_engine_spec_step"):
                    (emitted, acc, self._cache, sp["dcache"],
                     self._spec_pending, self._pos, self._remaining,
                     self._active, self._rng) = progs.step(
                        self.params, sp["dparams"], self._lora_arg(),
                        self._cache, sp["dcache"], self._spec_pending,
                        self._pos, self._remaining, self._active,
                        self._rng, self._temps, self._top_ps, self._stops,
                        self._adapter_idx, jnp.asarray(spec_rows), k=k,
                        mode=mode)
            out_rows.append(np.asarray(emitted).T)  # [k+1, S]  # dtxlint: disable=DTX001
            acc_np = np.asarray(acc)  # dtxlint: disable=DTX001
            # acc_np is host numpy already — no device sync here
            obs = [(s, int(acc_np[s]), k) for s in range(self.slots)  # dtxlint: disable=DTX001
                   if spec_rows[s] and active_prev[s]]
            self.spec_ctrl.observe(obs)
            self.spec_stats["spec_steps"] += 1
            self.spec_stats["row_steps"] += len(obs)
            for s, a, kk in obs:
                self.spec_stats["proposed"] += kk
                self.spec_stats["accepted"] += a
                if self._h_accept_len is not None:
                    self._h_accept_len.observe(a)
                if plan[0] == "tree":
                    ema_t = self._spec_tree_slot_path.get(s)
                    self._spec_tree_slot_path[s] = (
                        a * 1.0 if ema_t is None
                        else ema_t + self.spec_ctrl.alpha * (a - ema_t))
                req = self._slot_req[s]
                name = req.adapter_name if req is not None else ""
                ema = self._spec_adapter_ema.get(name)
                rate = a / kk
                # same smoothing as the controller's EMAs, so the adapter
                # gauge and the global/slot gauges agree on shared traffic
                alpha = self.spec_ctrl.alpha
                self._spec_adapter_ema[name] = (
                    rate if ema is None else ema + alpha * (rate - ema))
            if plan[0] == "tree" and self.spec_tree_learned and obs:
                # learned-shape inputs, from data already on host: the
                # fraction of drafting rows whose accepted path reached
                # depth ≥ j+1, and the fraction whose root top-2 logit
                # margin was decisive (draft-side early-exit signal)
                widths = plan[1]
                depth_fracs = [
                    sum(1 for _, a, _ in obs if a >= j + 1) / len(obs)
                    for j in range(len(widths))]
                margin_np = np.asarray(margin)  # dtxlint: disable=DTX001 — designed sync point: the tick already host-read obs at this boundary
                dm = [float(margin_np[s]) for s, _, _ in obs]  # dtxlint: disable=DTX001 — margin_np is host (np.asarray above)
                decisive_frac = sum(
                    1 for m in dm
                    if m >= self.spec_ctrl.DECISIVE_MARGIN) / len(dm)
                self.spec_ctrl.observe_tree(depth_fracs, decisive_frac)
            if self.sampling_epilogue == "on":
                self.sampling_stats["fused_steps"] += 1
            else:
                self.sampling_stats["legacy_steps"] += 1
            self._trace("spec", k, len(obs))
        else:
            emode = self._epilogue_mode()
            with jax.profiler.TraceAnnotation("dtx_engine_decode"):
                (emitted, self._cache, self._spec_pending, self._pos,
                 self._remaining, self._active, self._rng) = progs.decode(
                    self.params, self._lora_arg(), self._cache,
                    self._spec_pending, self._pos, self._remaining,
                    self._active, self._rng, self._temps, self._top_ps,
                    self._stops, self._adapter_idx, K=self.chunk,
                    mode=emode)
            out_rows.append(np.asarray(emitted))  # [K, S]  # dtxlint: disable=DTX001
            self.spec_stats["plain_steps"] += 1
            self.sampling_stats["fused_steps" if emode != "off"
                                else "legacy_steps"] += 1
            self.spec_ctrl.note_plain_step()
            self._trace("decode", self.chunk)

        active_np = np.asarray(self._active)  # dtxlint: disable=DTX001
        return np.concatenate(out_rows, axis=0), active_np

    def spec_info(self) -> Optional[dict]:
        """Speculative-decode observability document for stats()//metrics;
        None when no draft is configured."""
        if self.spec is None:
            return None
        snap = self.spec_ctrl.snapshot()
        info = {
            "enabled": True,
            "mode": self.spec_mode,
            "draft": self.spec["draft"],
            "k_max": self.spec_k,
            "k": snap["k"],
            "accept_rate": (round(snap["global_ema"], 4)
                            if snap["global_ema"] is not None else None),
            "adapter_accept_rate": {n: round(v, 4) for n, v in
                                    dict(self._spec_adapter_ema).items()},
            "slot_accept_rate": snap["slots"],
            "slots_off": snap["slots_off"],
            "active": self.spec_ctrl.use_spec(),
            "disabled_events": snap["disabled_events"],
        }
        if self.spec_tree is not None:
            plan = snap.get("plan") or []
            widths = (list(plan[1]) if len(plan) == 2 and plan[0] == "tree"
                      else [self.spec_tree.width] * self.spec_tree.depth)
            info["tree"] = {
                "spec": str(self.spec_tree),
                "width": self.spec_tree.width,
                "depth": self.spec_tree.depth,
                "learned": self.spec_tree_learned,
                # per-depth plan widths (dtx_serving_spec_tree_width{depth})
                "widths": widths,
                "plan_width": widths[0] if widths else self.spec_tree.width,
                "slot_path_len": {s: round(v, 4) for s, v in
                                  dict(self._spec_tree_slot_path).items()},
            }
            for key in ("depth_ema", "decisive_ema"):
                if key in snap:
                    info["tree"][key] = snap[key]
        info["sampling_epilogue"] = self.sampling_epilogue
        info["epilogue_impl"] = self._epilogue_impl
        info.update(self.sampling_stats)
        info.update(self.spec_stats)
        return info

    def _scheduler(self):
        while not self._shutdown.is_set():
            # migrations first: an imported session is already mid-decode
            # (its prefill budget was spent on the source replica), so it
            # outranks cold admissions for free slots
            self._service_migrations()
            self._resume_preempted_tick()
            self._admit_waiting()
            self._prefill_tick()
            self._grow_tick()

            if not any(self._decode_ready):
                if self._pending:
                    continue  # keep prefilling; nothing to decode yet
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue

            try:
                if self.spec is not None:
                    emitted_np, active_np = self._spec_decode_tick()
                else:
                    emode = self._epilogue_mode()
                    with jax.profiler.TraceAnnotation("dtx_engine_decode"):
                        (emitted, self._logits, self._cache, self._pos,
                         self._remaining, self._active, self._rng) = \
                            self._decode(
                                self.params, self._lora_arg(), self._cache,
                                self._logits, self._pos,
                                self._remaining, self._active, self._rng,
                                self._temps, self._top_ps, self._stops,
                                self._adapter_idx, K=self.chunk,
                                mode=emode,
                            )
                    self.sampling_stats["fused_steps" if emode != "off"
                                        else "legacy_steps"] += 1
                    self._trace("decode", self.chunk)
                    # the decode loop's ONE designed sync point: K tokens per
                    # chunk cross to host here so req.push can stream them
                    emitted_np = np.asarray(emitted)  # [K, S]  # dtxlint: disable=DTX001
                    active_np = np.asarray(self._active)  # [S]  # dtxlint: disable=DTX001
            except Exception as e:  # noqa: BLE001 — device fault: fail all in-flight
                for slot, req in enumerate(self._slot_req):
                    if req is not None:
                        self._release_slot(slot)
                        self._complete(req, error=str(e))
                continue

            for k in range(emitted_np.shape[0]):
                for slot in range(self.slots):
                    # emitted_np is host-side numpy already — no device sync
                    t = int(emitted_np[k, slot])  # dtxlint: disable=DTX001
                    req = self._slot_req[slot]
                    if t >= 0 and req is not None:
                        req.push(t)
            for slot in range(self.slots):
                req = self._slot_req[slot]
                # pending-prefill slots are inactive by design — only slots
                # that entered this decode chunk can finish here
                if (req is not None and self._decode_ready[slot]
                        and not bool(active_np[slot])):
                    self._release_slot(slot)
                    if self.tracing:
                        req.mark("finish", slot=slot)
                    self._complete(req)
                    self._trace("finish", slot)

    # ---------------------------------------------------------------- API
    def submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        stop_ids: Optional[set] = None,
        adapter: str = "",
        trace_id: str = "",
        tenant: str = "",
    ) -> Request:
        known = self.adapter_ids
        if adapter not in known:
            raise KeyError(
                f"unknown adapter {adapter!r}; loaded: "
                f"{sorted(n for n in known if n)}"
            )
        # device index: fixed at submit for the static stack; dynamic-mode
        # names resolve (and pin) at ADMISSION — a resident slot seen here
        # could be evicted before the request reaches a cache slot
        idx = known[adapter] if self.adapter_registry is None else 0
        with self._adapter_req_lock:
            if (adapter in self.adapter_requests
                    or len(self.adapter_requests)
                    < self._adapter_requests_cap):
                self.adapter_requests[adapter] = \
                    self.adapter_requests.get(adapter, 0) + 1
        # tenancy: resolve the request's tenant (explicit name wins, else
        # the adapter maps through the directory); an unknown/absent tenant
        # stays anonymous and schedules exactly like a pre-tenancy request
        tenant_name, tier = "", "standard"
        if self.tenants is not None:
            spec = self.tenants.resolve(tenant=tenant, adapter=adapter)
            if spec is not None:
                tenant_name, tier = spec.name, spec.tier
            self._tenant_count(tenant_name or (tenant or ""),
                               "requests", 1)
            self._tenant_count(tenant_name or (tenant or ""),
                               "tokens_in", len(prompt_ids))
        stops = {int(s) for s in (stop_ids or set())}
        stops.add(int(self.tokenizer.eos_token_id))
        # every request gets a trace id (callers without one — bench, bare
        # generate() — still get a /debug/trace timeline); the gateway's
        # X-DTX-Trace-Id arrives here via serving/server.py or
        # InProcessReplica so one id follows the request end to end
        req = Request(prompt_ids, max_new_tokens, temperature, top_p, seed,
                      sorted(stops), idx, adapter_name=adapter,
                      trace_id=trace_id or f"dtx-{uuid.uuid4().hex[:16]}",
                      tenant=tenant_name or (tenant or ""),
                      tenant_tier=tier)
        self._waiting.put(req)
        self._wake.set()
        return req

    def generate(self, prompt_ids, timeout: float = 300.0, **kw) -> List[int]:
        req = self.submit(prompt_ids, **kw)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RuntimeError(req.error)
        return req.tokens

    def _encode_chat(self, messages: List[dict]):
        import json

        from datatunerx_tpu.serving.engine import encode_chat_messages

        # tiny LRU keyed by the serialized messages: usage reporting (the
        # serving response's prompt_tokens) and the in-process replica's
        # calibration feedback re-encode the prompt a request already
        # encoded — memoizing makes the count a dict hit instead of a
        # second O(prompt) tokenizer pass on the serving hot path
        try:
            key = json.dumps(messages, sort_keys=True)
        except (TypeError, ValueError):
            return encode_chat_messages(self.template, self.tokenizer,
                                        messages)
        with self._encode_memo_lock:
            hit = self._encode_memo.get(key)
            if hit is not None:
                self._encode_memo.move_to_end(key)
                return hit
        out = encode_chat_messages(self.template, self.tokenizer, messages)
        with self._encode_memo_lock:
            self._encode_memo[key] = out
            self._encode_memo.move_to_end(key)
            while len(self._encode_memo) > 32:
                self._encode_memo.popitem(last=False)
        return out

    def perplexity(self, prompt_ids: Sequence[int],
                   completion_ids: Sequence[int], adapter: str = "") -> dict:
        """Mean completion NLL under the (optionally adapter-indexed) model —
        the unmerged stack scores through the same lora_idx path decode uses."""
        from datatunerx_tpu.serving.engine import (
            nll_impl,
            nll_result,
            prepare_nll_inputs,
        )

        if adapter not in self.adapter_ids:
            raise KeyError(f"unknown adapter {adapter!r}")
        if not hasattr(self, "_nll"):
            def impl(params, lora, tokens, mask, aidx):
                return nll_impl(
                    params, self.cfg, tokens, mask, lora=lora,
                    lora_adapter_idx=(aidx[None] if lora is not None
                                      else None),
                )

            self._nll = jax.jit(impl)
        tokens, mask, _ = prepare_nll_inputs(
            list(prompt_ids), list(completion_ids),
            self.tokenizer.eos_token_id, self.max_seq_len,
        )
        # dynamic mode: pin the adapter across the scoring forward so LRU
        # eviction can't swap its weights out mid-read (load-on-miss runs
        # here too — scoring a cold adapter warms it for serving)
        pinned = False
        if self.adapter_registry is not None and adapter:
            # blocking acquire: scoring runs on a caller thread, so it can
            # afford to wait out a load-on-miss (which also warms the
            # adapter for serving)
            idx = self.adapter_registry.acquire(adapter, wait=True)
            if idx is None:
                raise RuntimeError(
                    "adapter pool exhausted (all slots pinned); retry")
            pinned = True
        else:
            idx = self.adapter_ids[adapter]
        try:
            nll_sum, n_tok = self._nll(
                self.params, self._lora_arg(), tokens, mask,
                jnp.asarray(idx, jnp.int32),
            )
            return nll_result(float(nll_sum), int(n_tok))
        finally:
            if pinned:
                self.adapter_registry.release(adapter)

    def chat(self, messages: List[dict], max_new_tokens: int = 128,
             temperature: float = 0.0, top_p: float = 1.0, seed: int = 0,
             adapter: str = "", trace_id: str = "",
             tenant: str = "") -> str:
        prompt_ids, stop_ids = self._encode_chat(messages)
        out = self.generate(prompt_ids, max_new_tokens=max_new_tokens,
                            temperature=temperature, top_p=top_p, seed=seed,
                            stop_ids=stop_ids, adapter=adapter,
                            trace_id=trace_id, tenant=tenant)
        return self.tokenizer.decode(out, skip_special_tokens=True)

    def chat_stream(self, messages: List[dict], max_new_tokens: int = 128,
                    temperature: float = 0.0, top_p: float = 1.0,
                    seed: int = 0, adapter: str = "", trace_id: str = "",
                    tenant: str = ""):
        """Yields text deltas as tokens stream off the decode chunks."""
        prompt_ids, stop_ids = self._encode_chat(messages)
        req = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, top_p=top_p, seed=seed,
                          stop_ids=stop_ids, adapter=adapter,
                          trace_id=trace_id, tenant=tenant)
        sent = ""
        acc: List[int] = []
        while True:
            t = req.stream.get()
            if t is None:
                break
            acc.append(t)
            text = self.tokenizer.decode(acc, skip_special_tokens=True)
            if len(text) > len(sent) and not text.endswith("�"):
                yield text[len(sent):]
                sent = text
        if req.error:
            raise RuntimeError(req.error)

    def close(self):
        self._shutdown.set()
        self._wake.set()
        self._thread.join(timeout=10)
        if self.adapter_registry is not None:
            # scheduler is down; reap any in-flight async loader threads
            self.adapter_registry.close()
        # fail any migration commands the scheduler will never service so
        # their callers don't sit out the full wait timeout (the scheduler
        # thread is joined above — nothing else touches the retry list now)
        pending = list(self._mig_retry)
        self._mig_retry = []  # dtxlint: disable=DTX006 — owner thread already joined
        while True:
            try:
                pending.append(self._mig_q.get_nowait())
            except queue.Empty:
                break
        for cmd in pending:
            cmd["_error"] = "engine shut down"
            cmd["_refused"] = False
            cmd["_done"].set()
        # preemption-parked sessions can never resume now — fail their
        # requests so consumers don't sit out their full wait timeout
        parked = list(self._preempted)
        self._preempted = []  # dtxlint: disable=DTX006 — owner thread already joined
        for entry in parked:
            entry["req"].finish(error="engine shut down")
