"""KV session wire format: serialize a live decode session for transfer.

The KV migration fabric (ROADMAP) moves an in-flight session between
replicas without re-prefilling: the source engine exports the slot's KV
prefix (a dense row, trimmed to its live cursor), the decode state that
makes resumption token-exact (next-token logits, the slot's live PRNG key,
position/remaining cursors, sampling params), the generated-token tail,
and the adapter *name* (PR 10: names are the stable cross-fleet identity —
pool slot indices are replica-local). The target allocates blocks, scatters
the row back in via ``paged_insert_row``, and decode continues as if the
session had never moved.

Wire encodings for the KV row:

  bf16  — the cache's native bf16 bytes (LOSSLESS: resumed decode is
          bit-identical to an undisturbed run). The default for bf16
          caches.
  int8  — the ``kv_quant`` representation (int8 values + per-vector f32
          scales over head_dim, ``ops/attention.py kv_quantize``). The
          default — and exact — encoding for ``kv_quant="int8"`` engines,
          whose cache already holds these bytes; selecting it for a bf16
          cache halves the payload but rounds the prefix through int8
          (bounded, but no longer bit-exact).

Cross-encoding imports are supported in every direction (bf16 wire into an
int8 cache re-quantizes through the same kv_quantize path; int8 wire into a
bf16 cache dequantizes), so heterogeneous fleets can still hand sessions
around. Payloads are JSON with base64 array bodies — they ride the admin
HTTP surface (``POST /admin/sessions/export`` / ``/import``).
"""

from __future__ import annotations

import base64
import hashlib
from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.ops.attention import kv_quantize
from datatunerx_tpu.ops.paged_attention import POS_SENTINEL, row_trim

PAYLOAD_KIND = "dtx-kv-session"
# fleet prefix tier (datatunerx_tpu/fleet/prefix_tier.py): a prefilled
# prefix-cache entry serialized for cross-replica publish/import. Same KV
# row encoding as a session payload, but no decode state — the importer
# builds a local _PrefixCache entry, not a live slot.
PREFIX_KIND = "dtx-kv-prefix"
PAYLOAD_VERSION = 1

# The error string a migrated-away request dies with. The gateway matches
# on it (gateway/replica_pool.py MIGRATED_MARKER keeps the same literal —
# it must survive an SSE error event crossing the wire as plain text) to
# splice the imported continuation instead of re-prefilling.
MIGRATED_SESSION = "session migrated"


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _body(arr, b64: bool):
    """One array body: base64 text for the JSON wire, or the host numpy
    array itself for payloads that never leave the process (the engine's
    preemption parking — paying a base64 round-trip to sit in a host list
    would be pure overhead)."""
    host = np.asarray(arr)  # dtxlint: disable=DTX001 — migration serialization point
    return _b64(host) if b64 else host


def _unb64(data, dtype, shape) -> np.ndarray:
    if isinstance(data, np.ndarray):  # raw in-process body (b64=False)
        arr = np.ascontiguousarray(data).reshape(-1).view(np.dtype(dtype))
    else:
        arr = np.frombuffer(base64.b64decode(data.encode()), dtype=dtype)
    if arr.size != int(np.prod(shape)):
        raise ValueError(
            f"kv payload body holds {arr.size} elements, shape {shape} "
            f"needs {int(np.prod(shape))}")
    return arr.reshape(shape)


def encode_payload(payload: dict) -> dict:
    """Make a payload JSON-wire-safe: base64-encode any raw numpy bodies a
    ``b64=False`` (in-process) export left behind. Idempotent — already-
    encoded payloads pass through untouched — so the export surface can
    apply it unconditionally before a payload crosses the admin HTTP
    wire (e.g. a gateway drain exporting preemption-parked sessions)."""
    out = dict(payload)
    if isinstance(out.get("logits"), np.ndarray):
        out["logits"] = _b64(np.asarray(out["logits"], np.float32))
    kv = out.get("kv")
    if isinstance(kv, dict):
        kv = dict(kv)
        for key in ("k", "v", "pos", "k_scale", "v_scale"):
            if isinstance(kv.get(key), np.ndarray):
                kv[key] = _b64(kv[key])
        out["kv"] = kv
    return out


def model_signature(cfg, kv_quant: Optional[str]) -> dict:
    """What must match (or be convertible) for an import to be correct."""
    return {"layers": cfg.num_layers, "kv_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim, "vocab": cfg.vocab_size,
            "kv_quant": kv_quant or ""}


def _check_model_sig(payload: dict, cfg) -> None:
    sig = payload.get("model_sig") or {}
    for key, want in (("layers", cfg.num_layers),
                      ("kv_heads", cfg.num_kv_heads),
                      ("head_dim", cfg.head_dim),
                      ("vocab", cfg.vocab_size)):
        if sig.get(key) != want:
            raise ValueError(
                f"session payload is from an incompatible model: "
                f"{key}={sig.get(key)} here {want}")


def check_signature(payload: dict, cfg) -> None:
    _check_model_sig(payload, cfg)
    if payload.get("kind") != PAYLOAD_KIND:
        raise ValueError(
            f"not a {PAYLOAD_KIND} payload (kind={payload.get('kind')!r})")
    if payload.get("version") != PAYLOAD_VERSION:
        raise ValueError(
            f"unsupported session payload version {payload.get('version')!r}")


def check_prefix_signature(payload: dict, cfg) -> None:
    _check_model_sig(payload, cfg)
    if payload.get("kind") != PREFIX_KIND:
        raise ValueError(
            f"not a {PREFIX_KIND} payload (kind={payload.get('kind')!r})")
    if payload.get("version") != PAYLOAD_VERSION:
        raise ValueError(
            f"unsupported prefix payload version {payload.get('version')!r}")


def prefix_fingerprint(adapter: str, prompt_ids: Sequence[int]) -> str:
    """Stable fleet-wide identity of a prefix entry: (adapter NAME, prompt
    token ids). Names, not pool indices — indices are replica-local."""
    h = hashlib.sha1()
    h.update(str(adapter or "").encode("utf-8", "replace"))
    h.update(b"\x00")
    h.update(np.asarray(list(prompt_ids), np.int64).tobytes())
    return h.hexdigest()


def pack_kv_row(row: Dict, cursor: int, wire: str, b64: bool = True) -> dict:
    """A dense row cache (``paged_extract_row`` output or a dense-cache
    slot slice) → JSON-safe wire doc, trimmed to the live ``cursor``.

    ``wire`` is "int8" or "bf16"; int8 input rows (kv_quant caches) are
    shipped as-is under "int8" (exact), and a bf16 row asked for "int8"
    goes through kv_quantize (the over-the-wire compression path).
    ``b64=False`` keeps the array bodies as host numpy (in-process
    payloads: engine preemption parking); ``encode_payload`` upgrades
    them to base64 if they ever need the wire."""
    row = row_trim(row, max(1, cursor))
    k, v = row["k"], row["v"]
    quantized_cache = "k_scale" in row
    if wire == "int8" and not quantized_cache:
        # host transfer happens inside kv_quantize's consumers; do the
        # quantization on device, then pull the small int8 bodies
        k, ks = kv_quantize(k)
        v, vs = kv_quantize(v)
    elif quantized_cache:
        wire = "int8"  # an int8 cache's bytes ARE the int8 wire encoding
        ks, vs = row["k_scale"], row["v_scale"]
    else:
        wire = "bf16"
        ks = vs = None
    # the migration path's designed host sync: one device_get per array
    k_np = np.asarray(k)  # dtxlint: disable=DTX001 — migration serialization point
    v_np = np.asarray(v)  # dtxlint: disable=DTX001 — migration serialization point
    pos_np = np.asarray(row["pos"], np.int32)  # dtxlint: disable=DTX001 — migration serialization point
    L, _, W, KV, d = k_np.shape
    doc = {
        "wire": wire, "width": int(W), "layers": int(L),
        "kv_heads": int(KV), "head_dim": int(d),
        "k": _b64(k_np) if b64 else k_np,
        "v": _b64(v_np) if b64 else v_np,
        "pos": _b64(pos_np) if b64 else pos_np,
    }
    if wire == "int8":
        doc["k_scale"] = _body(np.asarray(ks, np.float32), b64)  # dtxlint: disable=DTX001 — migration serialization point
        doc["v_scale"] = _body(np.asarray(vs, np.float32), b64)  # dtxlint: disable=DTX001 — migration serialization point
    return doc


def unpack_kv_row(doc: dict, full_width: int,
                  quantize: Optional[str]) -> Dict:
    """Wire doc → a dense row cache dict shaped for this engine's cache
    (``[L, 1, full_width, KV, d]`` + sentinel-padded positions), converting
    between int8 and bf16 encodings as the target's ``quantize`` demands."""
    L, W = int(doc["layers"]), int(doc["width"])
    KV, d = int(doc["kv_heads"]), int(doc["head_dim"])
    if W > full_width:
        raise ValueError(
            f"session KV depth {W} exceeds this replica's context "
            f"{full_width}")
    wire = doc.get("wire") or "bf16"
    shape = (L, 1, W, KV, d)
    if wire == "int8":
        k = _unb64(doc["k"], np.int8, shape)
        v = _unb64(doc["v"], np.int8, shape)
        ks = _unb64(doc["k_scale"], np.float32, shape[:-1])
        vs = _unb64(doc["v_scale"], np.float32, shape[:-1])
    elif wire == "bf16":
        k = _unb64(doc["k"], jnp.bfloat16, shape)
        v = _unb64(doc["v"], jnp.bfloat16, shape)
        ks = vs = None
    else:
        raise ValueError(f"unknown kv wire encoding {wire!r}")
    pos = _unb64(doc["pos"], np.int32, (1, W))

    def _pad(a: np.ndarray, fill=0) -> jnp.ndarray:
        widths = [(0, 0)] * a.ndim
        widths[2 if a.ndim >= 3 else 1] = (0, full_width - W)
        return jnp.asarray(np.pad(a, widths, constant_values=fill))

    row: Dict = {"pos": _pad(pos, fill=POS_SENTINEL)}
    if quantize == "int8":
        if wire != "int8":  # bf16 wire into an int8 cache: re-quantize
            kq, ks_j = kv_quantize(jnp.asarray(k))
            vq, vs_j = kv_quantize(jnp.asarray(v))
            k = np.asarray(kq)  # dtxlint: disable=DTX001 — migration deserialization point
            v = np.asarray(vq)  # dtxlint: disable=DTX001 — migration deserialization point
            ks = np.asarray(ks_j)  # dtxlint: disable=DTX001 — migration deserialization point
            vs = np.asarray(vs_j)  # dtxlint: disable=DTX001 — migration deserialization point
        row["k"], row["v"] = _pad(k), _pad(v)
        row["k_scale"], row["v_scale"] = _pad(ks), _pad(vs)
    else:
        if wire == "int8":  # int8 wire into a bf16 cache: dequantize
            k = (k.astype(np.float32) * ks[..., None])
            v = (v.astype(np.float32) * vs[..., None])
        row["k"] = _pad(k.astype(jnp.bfloat16))
        row["v"] = _pad(v.astype(jnp.bfloat16))
    return row


def pack_logits(logits, b64: bool = True):
    return _body(np.asarray(logits, np.float32), b64)  # dtxlint: disable=DTX001 — migration serialization point


def unpack_logits(payload: dict, vocab: int) -> jnp.ndarray:
    return jnp.asarray(_unb64(payload["logits"], np.float32, (vocab,)))


def build_payload(cfg, kv_quant: Optional[str], request: dict, row: Dict,
                  cursor, pos, remaining, rng, logits,
                  wire: Optional[str] = None, b64: bool = True) -> dict:
    """Assemble the full wire payload for one exported session.

    ``request`` carries the Request's host-side fields (trace_id, adapter
    name, prompt/token lists, sampling params); ``cursor``/``pos``/
    ``remaining``/``rng``/``logits`` are the slot's decode-state scalars,
    already device_get'd by the engine; ``row`` is the (device) dense KV
    row this function trims, encodes, and pulls to host. ``b64=False``
    keeps array bodies as raw numpy for payloads that stay in-process
    (engine preemption parking); ``encode_payload`` makes them wire-safe."""
    cursor = int(cursor)
    default_wire = "int8" if kv_quant == "int8" else "bf16"
    return {
        "kind": PAYLOAD_KIND, "version": PAYLOAD_VERSION,
        **request,
        "pos": int(pos), "remaining": int(remaining), "cursor": cursor,
        "rng": [int(x) for x in np.asarray(rng, np.uint32)],
        "logits": pack_logits(logits, b64=b64),
        "kv": pack_kv_row(row, cursor, wire or default_wire, b64=b64),
        "model_sig": model_signature(cfg, kv_quant),
    }


def normalize_payload(payload: dict, cfg) -> dict:
    """Validate an incoming payload against this engine's model and cast
    every scalar the import consumes to its canonical host type — the one
    place JSON-shaped input is trusted-but-verified."""
    check_signature(payload, cfg)
    out = dict(payload)
    out["cursor"] = int(payload["cursor"])
    out["pos"] = int(payload["pos"])
    out["remaining"] = int(payload["remaining"])
    out["max_new_tokens"] = int(payload.get("max_new_tokens",
                                            out["remaining"]))
    out["temperature"] = float(payload.get("temperature", 0.0))
    out["top_p"] = float(payload.get("top_p", 1.0))
    out["seed"] = int(payload.get("seed", 0))
    out["stop_ids"] = [int(s) for s in (payload.get("stop_ids") or [])]
    out["prompt_ids"] = [int(t) for t in (payload.get("prompt_ids") or [])]
    out["tokens"] = [int(t) for t in (payload.get("tokens") or [])]
    out["adapter"] = str(payload.get("adapter") or "")
    out["trace_id"] = str(payload.get("trace_id") or "")
    rng = payload.get("rng") or []
    if len(rng) != 2:
        raise ValueError("session payload rng must be a 2-word PRNG key")
    out["rng"] = [int(x) for x in rng]
    return out
