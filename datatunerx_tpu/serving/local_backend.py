"""Local serving backend: subprocess server per job + /healthz polling.

The ServingBackend implementation used by the local pipeline (CI/e2e/dev);
status() maps the server's health gate onto the vocabulary the FinetuneJob
controller polls (HEALTHY gate parity with reference
finetunejob_controller.go:423-424).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import urllib.request
from typing import Dict, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalServingBackend:
    def __init__(self, workdir: str, template: str = "vanilla",
                 extra_env: dict | None = None):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.template = template
        self.extra_env = extra_env or {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._ports: Dict[str, int] = {}
        self._lock = threading.Lock()

    def deploy(self, name: str, spec: dict) -> None:
        with self._lock:
            if name in self._procs:
                return
            port = _free_port()
            appdir = os.path.join(self.workdir, f"serve-{name}")
            os.makedirs(appdir, exist_ok=True)
            log = open(os.path.join(appdir, "log.txt"), "w")
            argv = [
                sys.executable, "-m", "datatunerx_tpu.serving.server",
                "--model_path", spec["model_path"],
                "--checkpoint_path", spec.get("checkpoint_path") or "",
                "--template", spec.get("template", self.template),
                "--port", str(port),
                "--quantization", spec.get("quantization") or "",
            ]
            if spec.get("slots"):
                argv += ["--slots", str(spec["slots"])]
            from datatunerx_tpu.operator.backends import _pkg_root

            env = dict(os.environ)
            env["PYTHONPATH"] = _pkg_root() + os.pathsep + env.get("PYTHONPATH", "")
            env.update(self.extra_env)
            self._procs[name] = subprocess.Popen(
                argv, cwd=appdir, stdout=log, stderr=subprocess.STDOUT, env=env
            )
            self._ports[name] = port

    def status(self, name: str) -> str:
        with self._lock:
            proc = self._procs.get(name)
            port = self._ports.get(name)
        if proc is None:
            return "NotFound"
        if proc.poll() is not None:
            return "FAILED"
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as resp:
                return json.load(resp).get("status", "PENDING")
        except Exception:
            return "PENDING"

    def endpoint(self, name: str) -> Optional[str]:
        port = self._ports.get(name)
        return f"http://127.0.0.1:{port}" if port else None

    def delete(self, name: str) -> None:
        with self._lock:
            proc = self._procs.pop(name, None)
            self._ports.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
