"""Local serving backend: subprocess server per job + /healthz polling.

The ServingBackend implementation used by the local pipeline (CI/e2e/dev);
status() maps the server's health gate onto the vocabulary the FinetuneJob
controller polls (HEALTHY gate parity with reference
finetunejob_controller.go:423-424).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import urllib.request
from typing import Dict, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalServingBackend:
    def __init__(self, workdir: str, template: str = "vanilla",
                 extra_env: dict | None = None):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.template = template
        self.extra_env = extra_env or {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._ports: Dict[str, int] = {}
        self._lock = threading.Lock()

    def deploy(self, name: str, spec: dict) -> None:
        with self._lock:
            if name in self._procs:
                return
            port = _free_port()
            appdir = os.path.join(self.workdir, f"serve-{name}")
            os.makedirs(appdir, exist_ok=True)
            log = open(os.path.join(appdir, "log.txt"), "w")
            replicas = int(spec.get("replicas") or 1)
            if replicas > 1 or spec.get("gateway"):
                # multi-replica serving: the gateway fronts N replica
                # subprocesses (routing/admission/failover, gateway/server.py)
                # behind the SAME /healthz + /chat/completions contract, so
                # status() and the scoring POST work unchanged
                argv = [
                    sys.executable, "-m", "datatunerx_tpu.gateway.server",
                    "--model_path", spec["model_path"],
                    "--checkpoint_path", spec.get("checkpoint_path") or "",
                    "--template", spec.get("template", self.template),
                    "--port", str(port),
                    "--quantization", spec.get("quantization") or "",
                    "--replicas", str(replicas),
                    "--policy", spec.get("policy") or "least_busy",
                    "--workdir", appdir,
                ]
                # disaggregation knobs are gateway-only: role here is a
                # comma cycle assigned across spawned replicas, and the
                # prefill threshold / fleet plane live in the router
                for key in ("role", "prefill_threshold", "fleet_prefix_mb",
                            "fleet_handoff", "fleet_spill"):
                    val = spec.get(key)
                    if val:
                        if isinstance(val, bool):
                            val = int(val)  # the gateway flags are ints
                        argv += [f"--{key}", str(val)]
            else:
                argv = [
                    sys.executable, "-m", "datatunerx_tpu.serving.server",
                    "--model_path", spec["model_path"],
                    "--checkpoint_path", spec.get("checkpoint_path") or "",
                    "--template", spec.get("template", self.template),
                    "--port", str(port),
                    "--quantization", spec.get("quantization") or "",
                ]
                if spec.get("role"):
                    # single server: one role (the webhook rejects cycles
                    # when there is no gateway to distribute them)
                    argv += ["--role", str(spec["role"])]
            if spec.get("slots"):
                argv += ["--slots", str(spec["slots"])]
            # paged-cache + adapter-pool tuning flows through the
            # serveConfig untouched (serving.server and gateway.server
            # both accept these); paged_kernel rides along so an operator
            # can pin the decode path per deployment ("auto" is default
            # and needs no spec entry)
            for key in ("kv_block_size", "kv_blocks", "kv_overcommit",
                        "prefill_chunk",
                        "prefill_token_budget", "adapter_pool",
                        "adapter_rank_max", "paged_kernel",
                        "spec_draft_config", "spec_k", "spec_mode",
                        "spec_tree", "sampling_epilogue",
                        # multi-tenant QoS plane: both servers accept these
                        # (the gateway forwards them to spawned replicas)
                        "tenants_config", "host_adapter_cache_mb"):
                if spec.get(key):
                    argv += [f"--{key}", str(spec[key])]
            from datatunerx_tpu.operator.backends import _pkg_root

            env = dict(os.environ)
            env["PYTHONPATH"] = _pkg_root() + os.pathsep + env.get("PYTHONPATH", "")
            env.update(self.extra_env)
            self._procs[name] = subprocess.Popen(
                argv, cwd=appdir, stdout=log, stderr=subprocess.STDOUT, env=env
            )
            self._ports[name] = port

    def status(self, name: str) -> str:
        with self._lock:
            proc = self._procs.get(name)
            port = self._ports.get(name)
        if proc is None:
            return "NotFound"
        if proc.poll() is not None:
            return "FAILED"
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as resp:
                return json.load(resp).get("status", "PENDING")
        except Exception:
            return "PENDING"

    def endpoint(self, name: str) -> Optional[str]:
        port = self._ports.get(name)
        return f"http://127.0.0.1:{port}" if port else None

    # ----------------------------------------------- gateway autoscaling
    def scale_hint(self, name: str) -> Optional[dict]:
        """The gateway's /autoscale summary, or None for single-server
        deployments / unreachable gateways (controller skips scaling)."""
        from datatunerx_tpu.gateway.autoscale import parse_hint

        url = self.endpoint(name)
        if not url:
            return None
        try:
            with urllib.request.urlopen(f"{url}/autoscale", timeout=2) as r:
                return parse_hint(json.load(r))
        except Exception:  # noqa: BLE001 — no hint is a safe no-op
            return None

    def scale(self, name: str, replicas: int) -> bool:
        url = self.endpoint(name)
        if not url:
            return False
        req = urllib.request.Request(
            f"{url}/admin/scale",
            data=json.dumps({"replicas": int(replicas)}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status == 200
        except Exception:  # noqa: BLE001
            return False

    def delete(self, name: str) -> None:
        with self._lock:
            proc = self._procs.pop(name, None)
            self._ports.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
