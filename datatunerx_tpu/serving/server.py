"""Serving HTTP server: OpenAI-ish ``/chat/completions`` + health gating.

Endpoint contract matches what the reference pipeline consumes
(reference finetunejob_controller.go:433 builds
``http://<svc>:8000/chat/completions``; the Scoring operator POSTs there).
Health semantics replace KubeRay's application-level HEALTHY gate
(finetunejob_controller.go:423-424): ``/healthz`` returns 503 until the model
is fully loaded, then 200 — so a k8s readinessProbe gives the same
"model actually loaded" guarantee.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ServingState:
    def __init__(self):
        self.engine = None
        self.error: Optional[str] = None
        self.model_path = ""


STATE = ServingState()


class Handler(BaseHTTPRequestHandler):
    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # echo the gateway's trace id so one id follows a request
        # operator → gateway → replica (gateway/server.py generates it)
        trace = self.headers.get("X-DTX-Trace-Id")
        if trace:
            self.send_header("X-DTX-Trace-Id", trace)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            if STATE.engine is not None:
                self._json(200, {"status": "HEALTHY", "model": STATE.model_path})
            elif STATE.error:
                self._json(500, {"status": "FAILED", "error": STATE.error})
            else:
                self._json(503, {"status": "LOADING"})
        elif self.path == "/v1/models":
            self._json(200, {"object": "list", "data": [
                {"id": STATE.model_path, "object": "model"}]})
        elif self.path == "/metrics":
            self._metrics()
        else:
            self._json(404, {"error": "not found"})

    def _metrics(self):
        """Prometheus text exposition: prefill/prefix-cache counters (batched
        engine). Serving-side twin of the operator's /metrics endpoint."""
        lines = [
            "# TYPE dtx_serving_up gauge",
            f"dtx_serving_up {1 if STATE.engine is not None else 0}",
        ]
        eng = STATE.engine
        stats = getattr(eng, "prefill_stats", None)
        if stats is not None:
            lines.append("# TYPE dtx_serving_prefill_total counter")
            for kind, n in sorted(stats.items()):
                lines.append(
                    f'dtx_serving_prefill_total{{kind="{kind}"}} {n}')
            # hit = exact reuse, partial = suffix extension, miss = full;
            # .get so a partially-populated stats dict (engine mid-init or a
            # duck-typed test engine) can't 500 the scrape
            lines.append("# TYPE dtx_serving_prefix_cache_hits_total counter")
            lines.append(
                f"dtx_serving_prefix_cache_hits_total {stats.get('reuse', 0)}")
            lines.append(
                "# TYPE dtx_serving_prefix_cache_partial_hits_total counter")
            lines.append(
                "dtx_serving_prefix_cache_partial_hits_total "
                f"{stats.get('extend', 0)}")
            lines.append("# TYPE dtx_serving_prefix_cache_misses_total counter")
            lines.append(
                f"dtx_serving_prefix_cache_misses_total {stats.get('full', 0)}")
        prefix = getattr(eng, "_prefix", None)
        if prefix is not None:
            lines.append("# TYPE dtx_serving_prefix_cache_entries gauge")
            lines.append(f"dtx_serving_prefix_cache_entries {len(prefix)}")
            lines.append(
                "# TYPE dtx_serving_prefix_cache_evictions_total counter")
            lines.append(
                f"dtx_serving_prefix_cache_evictions_total {prefix.evictions}")
        if eng is not None and hasattr(eng, "_slot_req"):
            busy = sum(1 for r in eng._slot_req if r is not None)
            lines.append("# TYPE dtx_serving_slots_busy gauge")
            lines.append(f"dtx_serving_slots_busy {busy}")
            lines.append("# TYPE dtx_serving_slots_total gauge")
            lines.append(f"dtx_serving_slots_total {eng.slots}")
        # paged KV cache: FREE BLOCKS are the real admission headroom (the
        # gateway prefers this gauge over free slots — a slot is cheap, the
        # blocks behind it are not)
        if getattr(eng, "total_kv_blocks", None):
            lines.append("# TYPE dtx_serving_kv_blocks_free gauge")
            lines.append(f"dtx_serving_kv_blocks_free {eng.free_kv_blocks}")
            lines.append("# TYPE dtx_serving_kv_blocks_total gauge")
            lines.append(f"dtx_serving_kv_blocks_total {eng.total_kv_blocks}")
        body = ("\n".join(lines) + "\n").encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        if self.path == "/perplexity":
            self._perplexity()
            return
        if self.path not in ("/chat/completions", "/v1/chat/completions"):
            self._json(404, {"error": "not found"})
            return
        if STATE.engine is None:
            self._json(503, {"error": "model not loaded"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            messages = req.get("messages")
            if not isinstance(messages, list) or not messages:
                self._json(400, {"error": "messages must be a non-empty list"})
                return
            kwargs = dict(
                max_new_tokens=int(req.get("max_tokens", 128)),
                temperature=float(req.get("temperature", 0.0)),
                top_p=float(req.get("top_p", 1.0)),
            )
            # "model" routes to a named LoRA adapter on batched engines
            # (multi-tenant serving; unknown names 400 rather than silently
            # serving the base)
            adapter = req.get("model") or ""
            if adapter and getattr(STATE.engine, "adapter_ids", None) is not None:
                if adapter == STATE.model_path:
                    adapter = ""
                elif adapter not in STATE.engine.adapter_ids:
                    self._json(400, {"error": f"unknown model/adapter {adapter!r}"})
                    return
                kwargs["adapter"] = adapter
            if req.get("stream"):
                self._stream_chat(messages, kwargs)
                return
            text = STATE.engine.chat(messages, **kwargs)
            self._json(200, {
                "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": STATE.model_path,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }],
            })
        except Exception as e:  # noqa: BLE001 - serving must answer, not die
            self._json(500, {"error": str(e)})

    def _perplexity(self):
        """POST {"prompt": str, "completion": str[, "model": adapter]} →
        completion NLL/perplexity under the served model. Backs the
        perplexity metric of dataset-driven scoring."""
        if STATE.engine is None:
            self._json(503, {"error": "model not loaded"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = req.get("prompt") or ""
            completion = req.get("completion") or ""
            if not completion:
                self._json(400, {"error": "completion is required"})
                return
            tok = STATE.engine.tokenizer
            p_ids = tok.encode(prompt) if prompt else []
            try:
                c_ids = tok.encode(completion, add_special_tokens=False)
            except TypeError:  # tokenizers without the kwarg
                c_ids = tok.encode(completion)
            kwargs = {}
            adapter = req.get("model") or ""
            if adapter and getattr(STATE.engine, "adapter_ids", None) is not None:
                if adapter not in STATE.engine.adapter_ids:
                    self._json(400, {"error": f"unknown model/adapter {adapter!r}"})
                    return
                kwargs["adapter"] = adapter
            self._json(200, STATE.engine.perplexity(p_ids, c_ids, **kwargs))
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": str(e)})

    def _stream_chat(self, messages, kwargs):
        """SSE: one ``data: {chat.completion.chunk}`` event per text delta,
        then ``data: [DONE]`` (OpenAI stream shape)."""
        stream_fn = getattr(STATE.engine, "chat_stream", None)
        if stream_fn is None:  # single-slot engine: one terminal delta
            def stream_fn(msgs, **kw):
                yield STATE.engine.chat(msgs, **kw)
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        trace = self.headers.get("X-DTX-Trace-Id")
        if trace:
            self.send_header("X-DTX-Trace-Id", trace)
        self.end_headers()

        def event(payload: dict):
            self.wfile.write(b"data: " + json.dumps(payload).encode() + b"\n\n")
            self.wfile.flush()

        try:
            try:
                for delta in stream_fn(messages, **kwargs):
                    event({
                        "id": rid, "object": "chat.completion.chunk",
                        "created": int(time.time()), "model": STATE.model_path,
                        "choices": [{"index": 0,
                                     "delta": {"content": delta},
                                     "finish_reason": None}],
                    })
                event({
                    "id": rid, "object": "chat.completion.chunk",
                    "created": int(time.time()), "model": STATE.model_path,
                    "choices": [{"index": 0, "delta": {},
                                 "finish_reason": "stop"}],
                })
            except Exception as e:  # noqa: BLE001 — headers already sent:
                # a second HTTP response would corrupt the stream, so errors
                # become a terminal SSE event instead
                event({"error": {"message": str(e)}})
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass

    def log_message(self, *a):
        pass


def load_engine_async(model_path, checkpoint_path, template, max_seq_len,
                      quantization=None, slots=4, decode_chunk=8,
                      adapters=None, kv_quant=None, prefix_cache=0,
                      kv_block_size=0, kv_blocks=0, prefill_chunk=256,
                      prefill_token_budget=0):
    def _load():
        try:
            STATE.model_path = model_path
            batched = slots > 1 and not quantization
            # refusing beats silently serving the base model under a tenant's
            # adapter name / running a full-size cache the operator budgeted
            # HBM against
            for flag, val in (("--adapters", adapters),
                              ("--prefix_cache", prefix_cache),
                              ("--kv_quant", kv_quant),
                              ("--kv_block_size", kv_block_size)):
                if val and not batched:
                    raise ValueError(
                        f"{flag} requires the batched engine "
                        "(--slots > 1, no --quantization)"
                    )
            if batched:
                from datatunerx_tpu.serving.batched_engine import BatchedEngine

                STATE.engine = BatchedEngine(
                    model_path, checkpoint_path or None, adapters=adapters,
                    template=template, max_seq_len=max_seq_len,
                    slots=slots, decode_chunk=decode_chunk,
                    kv_quant=kv_quant or None, prefix_cache=prefix_cache,
                    kv_block_size=kv_block_size, kv_blocks=kv_blocks or None,
                    prefill_chunk=prefill_chunk,
                    prefill_token_budget=prefill_token_budget,
                )
            else:
                # single-slot path also carries serve-time quantization
                from datatunerx_tpu.serving.engine import InferenceEngine

                STATE.engine = InferenceEngine(
                    model_path, checkpoint_path or None, template=template,
                    max_seq_len=max_seq_len, quantization=quantization or None,
                )
        except Exception as e:  # noqa: BLE001
            STATE.error = str(e)

    t = threading.Thread(target=_load, daemon=True)
    t.start()
    return t


def parse_adapters(spec: str) -> dict:
    """--adapters name=ckpt_path[,name=path…]"""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, path = part.partition("=")
        if not name or not path:
            raise ValueError(f"bad adapter spec {part!r}; want name=path")
        out[name] = path
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="datatunerx-tpu-serving")
    p.add_argument("--model_path", required=True)
    p.add_argument("--checkpoint_path", default="")
    p.add_argument("--template", default="llama2")
    p.add_argument("--max_seq_len", type=int, default=1024)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--quantization", default="",
                   choices=["", "int8", "int4", "nf4"],
                   help="serve-time base-weight quantization")
    p.add_argument("--slots", type=int, default=4,
                   help="continuous-batching cache slots (1 = single-request engine)")
    p.add_argument("--decode_chunk", type=int, default=8,
                   help="tokens per decode program (admission latency bound)")
    p.add_argument("--adapters", default="",
                   help="named LoRA adapters: name=ckpt[,name=ckpt…]; "
                        "requests select one via the 'model' field")
    p.add_argument("--kv_quant", default="", choices=["", "int8"],
                   help="int8-quantized KV cache: half the cache HBM, double "
                        "the slots×context budget (batched engine only)")
    p.add_argument("--prefix_cache", type=int, default=0,
                   help="LRU entries of reusable prefilled prompt prefixes "
                        "(shared system prompts / repeated probes skip "
                        "prefill; batched engine only; costs one cache row "
                        "of HBM per entry)")
    p.add_argument("--kv_block_size", type=int, default=0,
                   help="paged KV cache block size in tokens (0 = dense "
                        "slots×max_seq_len cache); admission reserves "
                        "blocks, not full-width rows — see README "
                        "'Serving performance' for the HBM math")
    p.add_argument("--kv_blocks", type=int, default=0,
                   help="total blocks in the paged pool (default "
                        "slots × max_seq_len / kv_block_size; set lower to "
                        "serve the same slots in less HBM)")
    p.add_argument("--prefill_chunk", type=int, default=256,
                   help="chunked-prefill program length in tokens (paged "
                        "engine); long prompts prefill in chunks "
                        "interleaved with decode")
    p.add_argument("--prefill_token_budget", type=int, default=0,
                   help="max prefill tokens the scheduler spends between "
                        "decode chunks (0 = unbounded); bounds the TPOT "
                        "hit a long admission can inflict on in-flight "
                        "requests")
    args = p.parse_args(argv)

    load_engine_async(args.model_path, args.checkpoint_path, args.template,
                      args.max_seq_len, quantization=args.quantization,
                      slots=args.slots, decode_chunk=args.decode_chunk,
                      adapters=parse_adapters(args.adapters),
                      kv_quant=args.kv_quant, prefix_cache=args.prefix_cache,
                      kv_block_size=args.kv_block_size,
                      kv_blocks=args.kv_blocks,
                      prefill_chunk=args.prefill_chunk,
                      prefill_token_budget=args.prefill_token_budget)
    srv = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    print(f"[serving] listening on :{args.port} (model loading async)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
