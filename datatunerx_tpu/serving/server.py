"""Serving HTTP server: OpenAI-ish ``/chat/completions`` + health gating.

Endpoint contract matches what the reference pipeline consumes
(reference finetunejob_controller.go:433 builds
``http://<svc>:8000/chat/completions``; the Scoring operator POSTs there).
Health semantics replace KubeRay's application-level HEALTHY gate
(finetunejob_controller.go:423-424): ``/healthz`` returns 503 until the model
is fully loaded, then 200 — so a k8s readinessProbe gives the same
"model actually loaded" guarantee.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from datatunerx_tpu.obs.metrics import (
    Registry,
    adapter_load_histogram,
    exemplars_requested,
    serving_latency_histograms,
    spec_accept_len_histogram,
    set_build_info,
    set_uptime,
)
from datatunerx_tpu.obs.slo import SLOEvaluator, default_slos


class ServingState:
    def __init__(self):
        self.engine = None
        self.error: Optional[str] = None
        self.model_path = ""
        # disaggregation role this replica declares to the fleet:
        # "prefill" (long-prompt specialist), "decode", or "mixed" (the
        # default — role-less routing, byte-identical to older fleets).
        # Surfaced as dtx_serving_role{role=...}; the gateway's
        # HTTPReplica scrape keeps its routing view in sync.
        self.role = "mixed"
        # the server's ONE registry: engine latency histograms record into
        # it (load_engine_async passes it down) and every scrape-time gauge
        # is re-stated into it, so /metrics is a single exposition
        self.registry = Registry()
        self.started_at = time.monotonic()
        # serializes scrape-time gauge restating (concurrent scrapes would
        # race clear/set on the labeled counters)
        self.scrape_lock = threading.Lock()
        # SLO evaluator over this registry (obs/slo.py) — built lazily so
        # tests driving the Handler directly get a working /debug/slo, and
        # main() can install a --slo_config set before the first request
        self.slo: Optional[SLOEvaluator] = None
        self.slo_lock = threading.Lock()


STATE = ServingState()

# ceiling on slot-labeled series per family in the exposition: slots are a
# small fixed pool, so this never binds on a healthy engine — it is a guard
# against unbounded label cardinality if a spec_info document goes wrong
_SLOT_SERIES_CAP = 1024


def slo_evaluator() -> SLOEvaluator:
    """The server's evaluator, created on first use with the default
    serving objectives unless main() already installed a configured one."""
    with STATE.slo_lock:
        if STATE.slo is None:
            STATE.slo = SLOEvaluator(STATE.registry, default_slos("serving"))
        return STATE.slo


def metrics_text(with_exemplars: bool = True) -> str:
    """The /metrics body: scrape-time gauges re-stated into the shared
    registry next to the engine's live histograms. Factored off the HTTP
    handler so scripts/metrics_lint.py validates the same bytes a scraper
    sees. The HTTP wire defaults to with_exemplars=False (classic-parser
    safety); ``/metrics?exemplars=1`` opts in."""
    with STATE.scrape_lock:
        return _metrics_text_locked(with_exemplars)


def _metrics_text_locked(with_exemplars: bool = True) -> str:
    reg = STATE.registry
    eng = STATE.engine
    set_build_info(reg, "serving")
    set_uptime(reg, "serving", STATE.started_at)
    # dtx_slo_* verdict gauges: sample FIRST so window baselines advance
    # under scrape-only deployments (no /debug/slo poller, no sampler)
    ev = slo_evaluator()
    ev.sample()
    ev.restate_gauges(ev.evaluate())
    # declare the serving latency histograms even before the engine loads:
    # a scraper sees stable series from the first scrape (zero counts), and
    # an engine sharing this registry observes into these same objects
    # (one declaration site in obs.metrics — help text cannot diverge)
    serving_latency_histograms(reg)
    reg.gauge("dtx_serving_up", "1 once the model is fully loaded.").set(
        1 if eng is not None else 0)
    stats = getattr(eng, "prefill_stats", None)
    pf = reg.counter("dtx_serving_prefill_total",
                     "Admissions by prefill kind (full/reuse/extend).")
    # engine-derived series are re-stated per scrape — cleared first so a
    # swapped/reloaded engine can't leave stale samples behind
    hits = reg.counter("dtx_serving_prefix_cache_hits_total",
                       "Exact prefix-cache hits (prefill skipped).")
    partial = reg.counter("dtx_serving_prefix_cache_partial_hits_total",
                          "Strict-prefix hits (suffix-only prefill).")
    misses = reg.counter("dtx_serving_prefix_cache_misses_total",
                         "Full prefills.")
    evictions = reg.counter("dtx_serving_prefix_cache_evictions_total",
                            "Prefix-cache LRU evictions.")
    for c in (pf, hits, partial, misses, evictions):
        c.clear()
    if stats is not None:
        for kind, n in sorted(stats.items()):
            pf.set(n, {"kind": kind})
        # hit = exact reuse, partial = suffix extension, miss = full;
        # .get so a partially-populated stats dict (engine mid-init or a
        # duck-typed test engine) can't 500 the scrape
        hits.set(stats.get("reuse", 0))
        partial.set(stats.get("extend", 0))
        misses.set(stats.get("full", 0))
    prefix = getattr(eng, "_prefix", None)
    entries = reg.gauge("dtx_serving_prefix_cache_entries",
                        "Live prefix-cache entries.")
    entries.clear()
    if prefix is not None:
        entries.set(len(prefix))
        evictions.set(prefix.evictions)
    slots_busy = reg.gauge("dtx_serving_slots_busy",
                           "Cache slots holding an in-flight request.")
    # _capacity, not _total: the Prometheus _total suffix is reserved for
    # counters, and these are gauges (PR 7 naming unification — the old
    # dtx_serving_{slots,kv_blocks}_total names are gone; the gateway's
    # scrape parser accepts both during a rolling upgrade)
    slots_total = reg.gauge("dtx_serving_slots_capacity",
                            "Configured cache slots.")
    slots_busy.clear()
    slots_total.clear()
    if eng is not None and hasattr(eng, "_slot_req"):
        slots_busy.set(sum(1 for r in eng._slot_req if r is not None))
        slots_total.set(eng.slots)
    # paged KV cache: FREE BLOCKS are the real admission headroom (the
    # gateway prefers this gauge over free slots — a slot is cheap, the
    # blocks behind it are not)
    blocks_free = reg.gauge("dtx_serving_kv_blocks_free",
                            "Free paged KV-cache blocks.")
    blocks_total = reg.gauge("dtx_serving_kv_blocks_capacity",
                             "Total paged KV-cache blocks.")
    blocks_reserved = reg.gauge("dtx_serving_kv_blocks_reserved",
                                "Allocated paged KV-cache blocks (slots' "
                                "tables + COW prefix-cache entries).")
    block_size_g = reg.gauge("dtx_serving_kv_block_size",
                             "Tokens per paged KV block — the unit the "
                             "gateway's fleet-true admission prices "
                             "admits in.")
    over_ratio = reg.gauge("dtx_serving_kv_overcommit_ratio",
                           "Live sessions' eager-equivalent block demand "
                           "over the physical pool (> 1 = overcommitted; "
                           "only meaningful with --kv_overcommit on).")
    preempt = reg.counter("dtx_serving_preemptions_total",
                          "KV-overcommit preemptions by outcome (exported "
                          "= session parked host-side, resumed = parked "
                          "session re-admitted token-exactly, "
                          "requeued_prefill = mid-prefill admission "
                          "rolled back to the cold queue).")
    blocks_free.clear()
    blocks_total.clear()
    blocks_reserved.clear()
    block_size_g.clear()
    over_ratio.clear()
    preempt.clear()
    if getattr(eng, "total_kv_blocks", None):
        blocks_free.set(eng.free_kv_blocks)
        blocks_total.set(eng.total_kv_blocks)
        reserved = getattr(eng, "kv_blocks_reserved", None)
        if reserved is None:
            reserved = eng.total_kv_blocks - eng.free_kv_blocks
        blocks_reserved.set(reserved)
        block_size_g.set(getattr(eng, "block_size", 0) or 0)
        ratio = getattr(eng, "kv_overcommit_ratio", None)
        if ratio is not None:
            over_ratio.set(ratio)
    pstats = getattr(eng, "preempt_stats", None)
    if isinstance(pstats, dict):
        for outcome, np_ in sorted(pstats.items()):
            preempt.set(np_, {"outcome": outcome})
    # disaggregated fleet plane: the role this replica declares (one-hot
    # label the gateway's role-aware routing scrapes) and the parked-
    # session backlog the fleet spill coordinator treats as work
    role_g = reg.gauge("dtx_serving_role",
                       "Replica disaggregation role, one-hot by label "
                       "(prefill / decode / mixed).")
    parked_g = reg.gauge("dtx_serving_sessions_parked",
                         "Preemption-parked sessions awaiting resume — "
                         "the fleet spill coordinator's work signal.")
    role_g.clear()
    role_g.set(1, {"role": STATE.role})
    parked_g.set(int(getattr(eng, "parked_sessions", 0) or 0))
    # dynamic adapter pool (datatunerx_tpu/adapters/): occupancy, the
    # residency set the gateway's cache-locality routing scrapes, and
    # per-adapter traffic. Declared/cleared on every scrape so a swapped
    # engine or an unloaded adapter can't leave stale series behind.
    adapter_load_histogram(reg)  # stable series even pre-engine-load
    pool_cap = reg.gauge("dtx_serving_adapter_pool_slots_capacity",
                         "Adapter pool slots (loadable adapters resident "
                         "at once; the base model is not a slot).")
    pool_free = reg.gauge("dtx_serving_adapter_pool_slots_free",
                          "Adapter pool slots holding no adapter.")
    resident_g = reg.gauge("dtx_serving_adapter_resident",
                           "1 per adapter resident in the pool "
                           "(load-on-miss already paid).")
    registered_g = reg.gauge("dtx_serving_adapter_registered",
                             "1 per adapter this replica can serve "
                             "(resident or loadable on miss).")
    a_loads = reg.counter("dtx_serving_adapter_loads_total",
                          "Adapters materialised into pool slots "
                          "(checkpoint load + device insert).")
    a_evict = reg.counter("dtx_serving_adapter_evictions_total",
                          "Unpinned residents LRU-evicted to make room.")
    a_hits = reg.counter("dtx_serving_adapter_hits_total",
                         "Admissions whose adapter was already resident.")
    a_miss = reg.counter("dtx_serving_adapter_misses_total",
                         "Admissions that had to load their adapter.")
    a_reqs = reg.counter("dtx_serving_adapter_requests_total",
                         "Requests per adapter name ('' = base model).")
    for m in (pool_cap, pool_free, resident_g, registered_g, a_loads,
              a_evict, a_hits, a_miss, a_reqs):
        m.clear()
    occ_fn = getattr(eng, "adapter_occupancy", None)
    occ = occ_fn() if callable(occ_fn) else None
    if occ:
        pool_cap.set(occ.get("slots", 0))
        pool_free.set(occ.get("free", 0))
        for name in occ.get("resident_adapters") or []:
            resident_g.set(1, {"adapter": name})
        for name in occ.get("registered_adapters") or []:
            registered_g.set(1, {"adapter": name})
        a_loads.set(occ.get("loads", 0))
        a_evict.set(occ.get("evictions", 0))
        a_hits.set(occ.get("hits", 0))
        a_miss.set(occ.get("misses", 0))
    # speculative decoding: proposal/acceptance counters + the acceptance-
    # rate EMAs (global, per adapter, per slot) the gateway's spec-friendly
    # routing reads. Declared every scrape (stable zero series on non-spec
    # engines), restated from the engine's spec_info document.
    spec_accept_len_histogram(reg)  # engine observes into this same object
    sp_enabled = reg.gauge("dtx_serving_spec_enabled",
                           "1 when speculative decoding is configured "
                           "(a draft model is loaded).")
    sp_active = reg.gauge("dtx_serving_spec_active",
                          "1 while the adaptive controller is actually "
                          "drafting (0 = fallen back to plain decode).")
    sp_k = reg.gauge("dtx_serving_spec_k",
                     "Current proposal depth k (adaptive, <= --spec_k).")
    sp_rate = reg.gauge("dtx_serving_spec_accept_rate",
                        "Global acceptance-rate EMA (accepted/proposed "
                        "per verify step).")
    sp_rate_adapter = reg.gauge("dtx_serving_spec_adapter_accept_rate",
                                "Acceptance-rate EMA per adapter name "
                                "('' = base model).")
    sp_rate_slot = reg.gauge("dtx_serving_spec_slot_accept_rate",
                             "Acceptance-rate EMA per live cache slot.")
    sp_prop = reg.counter("dtx_serving_spec_proposed_total",
                          "Draft tokens proposed to the verifier.")
    sp_acc = reg.counter("dtx_serving_spec_accepted_total",
                         "Proposed tokens the target accepted.")
    sp_steps = reg.counter("dtx_serving_spec_steps_total",
                           "Decode programs run by path (spec = draft/"
                           "verify, plain = pending-form fallback).")
    # tree-draft families: declared every scrape like the rest (stable
    # zeros on chain-only engines), restated from spec_info()["tree"]
    sp_tree_steps = reg.counter("dtx_serving_spec_tree_steps_total",
                                "Verify steps that ran the tree-draft "
                                "program (vs chain draft/verify).")
    sp_tree_width = reg.gauge("dtx_serving_spec_tree_width",
                              "Current tree branch width per draft depth "
                              "(learned/adaptive, <= the --spec_tree W; "
                              "label depth is 1-based).")
    sp_tree_depth = reg.gauge("dtx_serving_spec_tree_depth",
                              "Configured tree draft depth D (0 = chain "
                              "drafts).")
    sp_tree_path = reg.gauge("dtx_serving_spec_tree_slot_path_len",
                             "Accepted root-to-leaf path length EMA per "
                             "live cache slot.")
    # fused sampling epilogue (ops/pallas_sampling.py): resolved mode +
    # decode ticks by sampler path — the epilogue-on/off bench twin reads
    # these to prove which path actually ran
    sp_epilogue = reg.gauge("dtx_serving_sampling_epilogue",
                            "Fused sampling epilogue state: 0 = off "
                            "(legacy host sampler), 1 = on via the XLA "
                            "oracle, 2 = on via the Pallas kernel.")
    sp_fused = reg.counter("dtx_serving_sampling_fused_steps_total",
                           "Decode/spec ticks by sampler path (fused = "
                           "on-chip epilogue, legacy = host argsort).")
    for m in (sp_enabled, sp_active, sp_k, sp_rate, sp_rate_adapter,
              sp_rate_slot, sp_prop, sp_acc, sp_steps, sp_tree_steps,
              sp_tree_width, sp_tree_depth, sp_tree_path, sp_epilogue,
              sp_fused):
        m.clear()
    spec_fn = getattr(eng, "spec_info", None)
    spec_doc = spec_fn() if callable(spec_fn) else None
    sp_enabled.set(1 if spec_doc else 0)
    if spec_doc:
        sp_active.set(1 if spec_doc.get("active") else 0)
        sp_k.set(spec_doc.get("k", 0))
        if spec_doc.get("accept_rate") is not None:
            sp_rate.set(spec_doc["accept_rate"])
        for name, v in sorted(
                (spec_doc.get("adapter_accept_rate") or {}).items()):
            sp_rate_adapter.set(v, {"adapter": name})
        # per-slot series are pruned on slot release engine-side; the cap
        # here bounds exposition cardinality even if an engine misbehaves
        for slot, v in sorted(
                (spec_doc.get("slot_accept_rate") or {}).items()
                )[:_SLOT_SERIES_CAP]:
            sp_rate_slot.set(v, {"slot": str(slot)})
        sp_prop.set(spec_doc.get("proposed", 0))
        sp_acc.set(spec_doc.get("accepted", 0))
        sp_steps.set(spec_doc.get("spec_steps", 0), {"path": "spec"})
        sp_steps.set(spec_doc.get("plain_steps", 0), {"path": "plain"})
        sp_tree_steps.set(spec_doc.get("tree_steps", 0))
        tree_doc = spec_doc.get("tree")
        if tree_doc:
            widths = (tree_doc.get("widths") or
                      [tree_doc.get("plan_width", 0)])
            for j, w in enumerate(widths):
                sp_tree_width.set(w, {"depth": str(j + 1)})
            sp_tree_depth.set(tree_doc.get("depth", 0))
            for slot, v in sorted(
                    (tree_doc.get("slot_path_len") or {}).items()
                    )[:_SLOT_SERIES_CAP]:
                sp_tree_path.set(v, {"slot": str(slot)})
    # the fused epilogue runs in plain decode too, spec or not — restate
    # from the engine, not the spec document
    impl = getattr(eng, "_epilogue_impl", "off")
    sp_epilogue.set({"off": 0, "xla": 1, "kernel": 2}.get(impl, 0))
    samp_stats = getattr(eng, "sampling_stats", None)
    if isinstance(samp_stats, dict):
        sp_fused.set(samp_stats.get("fused_steps", 0), {"path": "fused"})
        sp_fused.set(samp_stats.get("legacy_steps", 0), {"path": "legacy"})
    # KV migration fabric: session export/import outcomes (restated from
    # the engine's scheduler-thread counters, cleared first like the rest)
    s_exp = reg.counter("dtx_serving_session_export_total",
                        "Live decode sessions exported for replica-to-"
                        "replica handoff, by outcome.")
    s_imp = reg.counter("dtx_serving_session_import_total",
                        "Exported sessions imported (re-prefill-free "
                        "resume), by outcome.")
    s_exp.clear()
    s_imp.clear()
    sess_stats = getattr(eng, "session_stats", None)
    if isinstance(sess_stats, dict):
        for outcome, n in sorted((sess_stats.get("export") or {}).items()):
            s_exp.set(n, {"outcome": outcome})
        for outcome, n in sorted((sess_stats.get("import") or {}).items()):
            s_imp.set(n, {"outcome": outcome})
    # multi-tenant QoS plane: per-tenant usage + the host-RAM adapter
    # tier's load split. BOTH families are created only when their plane
    # is configured — a tenancy-less engine's scrape must stay
    # byte-identical (the PR 15/16 gating contract).
    usage_fn = getattr(eng, "tenant_usage", None)
    usage = usage_fn() if callable(usage_fn) else None
    if usage is not None:
        t_reqs = reg.counter("dtx_serving_tenant_requests_total",
                             "Requests per tenant ('' = anonymous).")
        t_toks = reg.counter("dtx_serving_tenant_tokens_total",
                             "Tokens per tenant by direction (in = "
                             "prompt, out = generated).")
        t_blocks = reg.gauge("dtx_serving_tenant_kv_blocks",
                             "Live paged KV blocks held by the tenant's "
                             "in-flight sessions.")
        t_res = reg.gauge("dtx_serving_tenant_adapters_resident",
                          "The tenant's adapters currently resident in "
                          "the pool.")
        t_tier = reg.gauge("dtx_serving_tenant_tier",
                           "Tenant tier, one-hot by label "
                           "(pinned / standard / bulk).")
        for m in (t_reqs, t_toks, t_blocks, t_res, t_tier):
            m.clear()
        for tname, row in sorted(usage.items()):
            lbl = {"tenant": tname}
            t_reqs.set(row.get("requests", 0), lbl)
            t_toks.set(row.get("tokens_in", 0),
                       {"tenant": tname, "direction": "in"})
            t_toks.set(row.get("tokens_out", 0),
                       {"tenant": tname, "direction": "out"})
            if "kv_blocks" in row:
                t_blocks.set(row["kv_blocks"], lbl)
            if "adapters_resident" in row:
                t_res.set(row["adapters_resident"], lbl)
            if row.get("tier"):
                t_tier.set(1, {"tenant": tname, "tier": row["tier"]})
    host_fn = getattr(getattr(eng, "adapter_registry", None),
                      "host_tier_stats", None)
    host = host_fn() if callable(host_fn) else None
    if host is not None:
        h_hits = reg.counter("dtx_serving_adapter_host_hits_total",
                             "Adapter loads served from the host-RAM "
                             "tier (no orbax read).")
        h_orbax = reg.counter("dtx_serving_adapter_orbax_loads_total",
                              "Adapter loads that paid the orbax "
                              "checkpoint read.")
        h_evict = reg.counter("dtx_serving_adapter_host_evictions_total",
                              "Host-tier entries evicted to fit newer "
                              "weights under the byte budget.")
        h_bytes = reg.gauge("dtx_serving_adapter_host_bytes",
                            "Bytes of adapter weights cached in the "
                            "host-RAM tier.")
        h_entries = reg.gauge("dtx_serving_adapter_host_entries",
                              "Adapters cached in the host-RAM tier.")
        for m in (h_hits, h_orbax, h_evict, h_bytes, h_entries):
            m.clear()
        h_hits.set(host.get("host_hits", 0))
        h_orbax.set(host.get("orbax_loads", 0))
        h_evict.set(host.get("evictions", 0))
        h_bytes.set(host.get("bytes", 0))
        h_entries.set(host.get("entries", 0))
    # per-adapter demand: prefer the occupancy doc's LOCK-GUARDED copy
    # (dynamic engines); static engines snapshot under the engine's own
    # lock — copying the live dict bare would race a concurrent submit
    reqs = (occ or {}).get("requests")
    if reqs is None:
        raw = getattr(eng, "adapter_requests", None)
        if raw:
            lock = getattr(eng, "_adapter_req_lock", None)
            if lock is not None:
                with lock:
                    reqs = dict(raw)
            else:
                reqs = dict(raw)
    for name, n in sorted((reqs or {}).items()):
        a_reqs.set(n, {"adapter": name})
    return reg.expose(with_exemplars=with_exemplars)


class Handler(BaseHTTPRequestHandler):
    def _json(self, code: int, payload: dict):
        # count BEFORE the body goes out so a scrape racing the response
        # can't miss its own request (gateway/server.py does the same)
        self._record(code)
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # echo the gateway's trace id so one id follows a request
        # operator → gateway → replica (gateway/server.py generates it)
        trace = self.headers.get("X-DTX-Trace-Id")
        if trace:
            self.send_header("X-DTX-Trace-Id", trace)
        self.end_headers()
        self.wfile.write(body)

    def _record(self, code: int):
        STATE.registry.counter(
            "dtx_serving_requests_total",
            "Requests by terminal HTTP code (gateway-parity naming).").inc(
            {"code": str(code)})

    def do_GET(self):
        if self.path == "/healthz":
            if STATE.engine is not None:
                self._json(200, {"status": "HEALTHY", "model": STATE.model_path})
            elif STATE.error:
                self._json(500, {"status": "FAILED", "error": STATE.error})
            else:
                self._json(503, {"status": "LOADING"})
        elif self.path == "/v1/models":
            self._json(200, {"object": "list", "data": [
                {"id": STATE.model_path, "object": "model"}]})
        elif self.path.split("?")[0] == "/metrics":
            self._metrics()
        elif self.path == "/admin/adapters":
            self._adapters_get()
        elif self.path == "/debug/slo":
            # same evaluator/report shape as the gateway's /debug/slo —
            # obs/slo.py is the single verdict implementation
            self._json(200, slo_evaluator().report(plane="serving"))
        elif self.path.startswith("/debug/trace/"):
            self._debug_trace(self.path[len("/debug/trace/"):])
        else:
            self._json(404, {"error": "not found"})

    # ------------------------------------------------- dynamic adapter plane
    def _adapters_get(self):
        """The replica's adapter inventory: registered names, resident set,
        pool occupancy + load/evict/hit/miss stats. 501 on engines without
        a dynamic pool (static --adapters stacks still report their fixed
        names)."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        occ_fn = getattr(eng, "adapter_occupancy", None)
        occ = occ_fn() if callable(occ_fn) else None
        if occ is None:
            ids = getattr(eng, "adapter_ids", None)
            self._json(200, {
                "dynamic": False,
                "registered": sorted(n for n in (ids or {}) if n),
                "resident": sorted(n for n in (ids or {}) if n),
            })
            return
        catalog_fn = getattr(eng, "adapter_catalog", None)
        self._json(200, {
            "dynamic": True,
            "registered": occ.pop("registered_adapters", []),
            "resident": occ.pop("resident_adapters", []),
            # name → checkpoint: what a replacement replica needs to
            # rebuild this warm set (ManagedReplicaSet drain inheritance)
            "checkpoints": (catalog_fn() if callable(catalog_fn) else {}),
            "pool": occ,
        })

    def _adapters_post(self, req: dict):
        """POST /admin/adapters {"name": n, "checkpoint": path[, "load":
        bool]} — register a tenant adapter at runtime; by default the
        weights are warmed into a pool slot immediately so the first
        request is a residency hit. 400 on geometry violations (rank >
        rank_max, foreign targets), 409 on a live-name conflict, 501 on
        static-stack engines."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        name = str(req.get("name") or "")
        ckpt = str(req.get("checkpoint") or "")
        if not name or not ckpt:
            self._json(400, {"error": "name and checkpoint are required"})
            return
        load = req.get("load", True)
        loader = getattr(eng, "load_adapter", None)
        if not callable(loader):
            self._json(501, {"error": "engine has no dynamic adapter pool"})
            return
        from datatunerx_tpu.adapters import AdapterPinnedError

        try:
            self._json(200, loader(name, ckpt, preload=bool(load)))
        except NotImplementedError as e:  # static stack: can never succeed
            self._json(501, {"error": str(e)})
        except AdapterPinnedError as e:
            self._json(409, {"error": str(e)})
        except RuntimeError as e:  # pool exhausted: retryable
            self._json(409, {"error": str(e)})
        except (ValueError, FileNotFoundError) as e:
            self._json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — serving must answer
            self._json(500, {"error": str(e)})

    def _adapters_delete(self, name: str):
        """DELETE /admin/adapters/<name> — evict + unregister. 409 while
        in-flight requests pin the adapter; retry after they drain."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        unloader = getattr(eng, "unload_adapter", None)
        if not callable(unloader):
            self._json(501, {"error": "engine has no dynamic adapter pool"})
            return
        from datatunerx_tpu.adapters import AdapterPinnedError

        try:
            if unloader(name):
                self._json(200, {"unloaded": name})
            else:
                self._json(404, {"error": f"no adapter {name!r}"})
        except NotImplementedError as e:
            self._json(501, {"error": str(e)})
        except AdapterPinnedError as e:
            self._json(409, {"error": str(e)})
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": str(e)})

    def do_DELETE(self):
        if self.path.startswith("/admin/adapters/"):
            self._adapters_delete(self.path[len("/admin/adapters/"):])
        else:
            self._json(404, {"error": "not found"})

    def _metrics(self):
        """Prometheus text exposition from the shared registry (obs.metrics):
        engine latency histograms + scrape-time gauges, one encoder.
        Exemplar annotations only on the ?exemplars=1 debug view (classic
        parsers reject the tail)."""
        # getattr: tests drive a bare Handler (no request line, no path)
        body = metrics_text(
            with_exemplars=exemplars_requested(
                getattr(self, "path", ""))).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _debug_trace(self, trace_id: str):
        """Per-request span timeline from the engine's trace ring — the
        replica half of the gateway's GET /debug/trace/<id> merge."""
        store = getattr(STATE.engine, "trace_store", None)
        doc = store.get(trace_id) if store is not None and trace_id else None
        if doc is None:
            self._json(404, {"error": f"no trace {trace_id!r}"})
        else:
            self._json(200, doc)

    def _debug_profile(self, req: dict):
        """Arm an N-second jax.profiler window (one at a time per process).
        Engine decode/prefill ticks are TraceAnnotation-labeled, so the
        capture reads like the scheduler's own timeline in XProf."""
        from datatunerx_tpu.obs.profiling import (
            process_profiler,
            resolve_profile_dir,
        )

        try:
            seconds = float(req.get("seconds", 2.0))
        except (TypeError, ValueError):
            self._json(400, {"error": "seconds must be a number"})
            return
        try:
            log_dir = resolve_profile_dir(str(req.get("dir") or ""))
        except ValueError as e:  # dir escapes the allowed root
            self._json(400, {"error": str(e)})
            return
        try:
            effective = process_profiler().start(log_dir, seconds)
        except Exception as e:  # noqa: BLE001 — profiler fault ≠ server fault
            self._json(500, {"error": f"profiler failed to start: {e}"})
            return
        if effective is None:
            self._json(409, {"error": "a profile capture is already running",
                             "active": process_profiler().status()})
            return
        # echo the CLAMPED window, not the request — what will actually run
        self._json(202, {"profiling": log_dir, "seconds": effective})

    # --------------------------------------------------- KV migration fabric
    def _sessions_export(self, req: dict):
        """POST /admin/sessions/export {"slots": [..]?, "wire":
        "bf16"|"int8"?, "prefill": bool?} — serialize (and terminate)
        in-flight decode sessions for replica-to-replica handoff;
        ``prefill`` additionally ships MID-chunked-prefill slots (blocks
        written so far + remaining prompt tail). 501 on engines without
        the migration surface."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        fn = getattr(eng, "export_sessions", None)
        if not callable(fn):
            self._json(501, {"error": "engine has no session export"})
            return
        kw = {"slots": req.get("slots"),
              "wire_quant": req.get("wire") or None}
        if req.get("prefill"):
            # only when asked: older engines lack the kwarg entirely
            kw["include_prefill"] = True
        try:
            self._json(200, fn(**kw))
        except TimeoutError as e:
            self._json(503, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — serving must answer
            self._json(500, {"error": str(e)})

    def _fleet_admin(self, attr: str, kwargs: dict):
        """Shared shell for the fleet-plane admin surfaces (spill leases
        + prefix tier). Engine refusals (ValueError/KeyError) map to 409
        — the coordinator's fall-back-or-retry signal — and a missing
        engine method to 501, which HTTPReplica reads as 'replica kind
        without the surface' (None, skipped quietly)."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        fn = getattr(eng, attr, None)
        if not callable(fn):
            self._json(501, {"error": f"engine has no {attr}"})
            return
        try:
            self._json(200, fn(**kwargs))
        except (ValueError, KeyError) as e:
            self._json(409, {"error": str(e)})
        except TimeoutError as e:
            self._json(503, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — serving must answer
            self._json(500, {"error": str(e)})

    def _sessions_hold(self, req: dict):
        """POST /admin/sessions/hold {"max_sessions": n, "hold_s": s} —
        lease preemption-parked sessions for a peer spill (phase 1)."""
        self._fleet_admin("hold_parked", {
            "max_sessions": int(req.get("max_sessions", 4)),
            "hold_s": float(req.get("hold_s", 10.0))})

    def _sessions_drop(self, req: dict):
        """POST /admin/sessions/drop {"trace_ids": [...]} — finish a
        spill: drop the re-homed sessions, terminating their source
        requests with the migrated marker."""
        self._fleet_admin("drop_parked", {
            "trace_ids": list(req.get("trace_ids") or [])})

    def _sessions_release(self, req: dict):
        """POST /admin/sessions/release {"trace_ids": [...]} — abort a
        spill: clear the leases so the sessions resume locally."""
        self._fleet_admin("release_parked", {
            "trace_ids": list(req.get("trace_ids") or [])})

    def _prefix_export(self, req: dict):
        """POST /admin/prefix/export {"max_entries": n, "exclude":
        [fp...], "wire": "bf16"|"int8"?} — publishable local prefix-cache
        entries for the fleet prefix tier."""
        self._fleet_admin("export_prefix_entries", {
            "exclude": req.get("exclude") or None,
            "max_entries": int(req.get("max_entries", 4)),
            "wire_quant": req.get("wire") or None})

    def _prefix_import(self, req: dict):
        """POST /admin/prefix/import <dtx-kv-prefix payload> — install a
        fleet-published prefix entry into the local prefix cache."""
        self._fleet_admin("import_prefix_entry", {"payload": dict(req)})

    def _sessions_import(self, req: dict):
        """POST /admin/sessions/import <payload> — admit an exported
        session and resume its decode. Default response is an SSE stream:
        first event ``{"imported": meta}``, then ``{"delta": text}``
        continuation events (text beyond the migrated tail), then
        ``[DONE]`` — one round-trip carries the receipt AND the spliced
        stream. ``"stream": false`` blocks until the session finishes and
        returns the full text (tooling/tests). 409 on a refusal the
        caller should fall back cold on (no slot, blocks exhausted,
        unknown adapter, incompatible payload)."""
        eng = STATE.engine
        if eng is None:
            self._json(503, {"error": "model not loaded"})
            return
        fn = getattr(eng, "import_session", None)
        if not callable(fn):
            self._json(501, {"error": "engine has no session import"})
            return
        stream = bool(req.pop("stream", True))
        try:
            meta = dict(fn(req))
        except (ValueError, KeyError) as e:
            self._json(409, {"error": str(e)})
            return
        except TimeoutError as e:
            self._json(503, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": str(e)})
            return
        handle = meta.pop("_request", None)
        if not stream:
            if handle is not None:
                handle.done.wait(300)
                meta["error"] = handle.error
                meta["text"] = eng.tokenizer.decode(
                    handle.tokens, skip_special_tokens=True)
            self._json(200, {"imported": meta})
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()

        def event(payload: dict):
            self.wfile.write(b"data: " + json.dumps(payload).encode()
                             + b"\n\n")
            self.wfile.flush()

        code = 200
        try:
            event({"imported": meta})
            try:
                if handle is not None:
                    for delta in eng.resume_stream(handle):
                        event({"delta": delta})
            except Exception as e:  # noqa: BLE001 — headers already sent
                event({"error": {"message": str(e)}})
                code = 500
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            code = 499
        self._record(code)

    def do_POST(self):
        if self.path == "/perplexity":
            self._perplexity()
            return
        fleet_routes = {
            "/admin/sessions/export": self._sessions_export,
            "/admin/sessions/import": self._sessions_import,
            "/admin/sessions/hold": self._sessions_hold,
            "/admin/sessions/drop": self._sessions_drop,
            "/admin/sessions/release": self._sessions_release,
            "/admin/prefix/export": self._prefix_export,
            "/admin/prefix/import": self._prefix_import,
        }
        if self.path in fleet_routes:
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            fleet_routes[self.path](req)
            return
        if self.path == "/admin/adapters":
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            self._adapters_post(req)
            return
        if self.path == "/debug/profile":
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            self._debug_profile(req)
            return
        if self.path not in ("/chat/completions", "/v1/chat/completions"):
            self._json(404, {"error": "not found"})
            return
        if STATE.engine is None:
            self._json(503, {"error": "model not loaded"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            messages = req.get("messages")
            if not isinstance(messages, list) or not messages:
                self._json(400, {"error": "messages must be a non-empty list"})
                return
            kwargs = dict(
                max_new_tokens=int(req.get("max_tokens", 128)),
                temperature=float(req.get("temperature", 0.0)),
                top_p=float(req.get("top_p", 1.0)),
            )
            # "model" routes to a named LoRA adapter on batched engines
            # (multi-tenant serving; unknown names 400 rather than silently
            # serving the base)
            adapter = req.get("model") or ""
            if adapter and getattr(STATE.engine, "adapter_ids", None) is not None:
                if adapter == STATE.model_path:
                    adapter = ""
                elif adapter not in STATE.engine.adapter_ids:
                    self._json(400, {"error": f"unknown model/adapter {adapter!r}"})
                    return
                kwargs["adapter"] = adapter
            # hand the gateway's trace id to engines that keep span
            # timelines (duck-typed/single-slot engines just don't get it)
            trace = self.headers.get("X-DTX-Trace-Id") or ""
            if trace and getattr(STATE.engine, "trace_store", None) is not None:
                kwargs["trace_id"] = trace
            # tenancy: hand the gateway's tenant name to engines running a
            # directory (everyone else never sees the kwarg)
            tenant = self.headers.get("X-DTX-Tenant") or ""
            if tenant and getattr(STATE.engine, "tenants", None) is not None:
                kwargs["tenant"] = tenant
            if req.get("stream"):
                self._stream_chat(messages, kwargs,
                                  usage=self._prompt_usage(messages))
                return
            usage = self._prompt_usage(messages)
            text = STATE.engine.chat(messages, **kwargs)
            body = {
                "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": STATE.model_path,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }],
            }
            if usage is not None:
                usage["completion_tokens"] = self._count_tokens(text)
                usage["total_tokens"] = (usage["prompt_tokens"]
                                         + usage["completion_tokens"])
                body["usage"] = usage
            self._json(200, body)
        except Exception as e:  # noqa: BLE001 - serving must answer, not die
            self._json(500, {"error": str(e)})

    @staticmethod
    def _prompt_usage(messages) -> Optional[dict]:
        """Replica-side tokenized prompt length — the TRUTHFUL count the
        gateway's admission calibrates with (the chars-per-token heuristic
        is a guess; this is what prefill actually pays). None on engines
        without the chat encoder (duck-typed stand-ins)."""
        enc = getattr(STATE.engine, "_encode_chat", None)
        if not callable(enc):
            return None
        try:
            return {"prompt_tokens": len(enc(messages)[0])}
        except Exception:  # noqa: BLE001 — usage is advisory
            return None

    @staticmethod
    def _count_tokens(text: str) -> int:
        tok = getattr(STATE.engine, "tokenizer", None)
        if tok is None or not text:
            return 0
        try:
            return len(tok.encode(text, add_special_tokens=False))
        except TypeError:  # tokenizers without the kwarg
            return len(tok.encode(text))
        except Exception:  # noqa: BLE001
            return 0

    def _perplexity(self):
        """POST {"prompt": str, "completion": str[, "model": adapter]} →
        completion NLL/perplexity under the served model. Backs the
        perplexity metric of dataset-driven scoring."""
        if STATE.engine is None:
            self._json(503, {"error": "model not loaded"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = req.get("prompt") or ""
            completion = req.get("completion") or ""
            if not completion:
                self._json(400, {"error": "completion is required"})
                return
            tok = STATE.engine.tokenizer
            p_ids = tok.encode(prompt) if prompt else []
            try:
                c_ids = tok.encode(completion, add_special_tokens=False)
            except TypeError:  # tokenizers without the kwarg
                c_ids = tok.encode(completion)
            kwargs = {}
            adapter = req.get("model") or ""
            if adapter and getattr(STATE.engine, "adapter_ids", None) is not None:
                if adapter not in STATE.engine.adapter_ids:
                    self._json(400, {"error": f"unknown model/adapter {adapter!r}"})
                    return
                kwargs["adapter"] = adapter
            self._json(200, STATE.engine.perplexity(p_ids, c_ids, **kwargs))
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": str(e)})

    def _stream_chat(self, messages, kwargs, usage=None):
        """SSE: one ``data: {chat.completion.chunk}`` event per text delta,
        then ``data: [DONE]`` (OpenAI stream shape). The terminal chunk
        carries ``usage`` (replica-side tokenized prompt length) so
        streaming clients — the gateway's HTTPReplica included — get the
        same truthful count the non-streamed response body does."""
        stream_fn = getattr(STATE.engine, "chat_stream", None)
        if stream_fn is None:  # single-slot engine: one terminal delta
            def stream_fn(msgs, **kw):
                yield STATE.engine.chat(msgs, **kw)
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        trace = self.headers.get("X-DTX-Trace-Id")
        if trace:
            self.send_header("X-DTX-Trace-Id", trace)
        self.end_headers()

        def event(payload: dict):
            self.wfile.write(b"data: " + json.dumps(payload).encode() + b"\n\n")
            self.wfile.flush()

        code = 200
        try:
            try:
                for delta in stream_fn(messages, **kwargs):
                    event({
                        "id": rid, "object": "chat.completion.chunk",
                        "created": int(time.time()), "model": STATE.model_path,
                        "choices": [{"index": 0,
                                     "delta": {"content": delta},
                                     "finish_reason": None}],
                    })
                terminal = {
                    "id": rid, "object": "chat.completion.chunk",
                    "created": int(time.time()), "model": STATE.model_path,
                    "choices": [{"index": 0, "delta": {},
                                 "finish_reason": "stop"}],
                }
                if usage is not None:
                    terminal["usage"] = usage
                event(terminal)
            except Exception as e:  # noqa: BLE001 — headers already sent:
                # a second HTTP response would corrupt the stream, so errors
                # become a terminal SSE event instead
                event({"error": {"message": str(e)}})
                code = 500
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            code = 499
        self._record(code)

    def log_message(self, *a):
        pass


def load_engine_async(model_path, checkpoint_path, template, max_seq_len,
                      quantization=None, slots=4, decode_chunk=8,
                      adapters=None, adapter_pool=0, adapter_rank_max=8,
                      adapter_targets=None, kv_quant=None, prefix_cache=0,
                      kv_block_size=0, kv_blocks=0, kv_overcommit="off",
                      prefill_chunk=256,
                      prefill_token_budget=0, paged_kernel="auto",
                      spec_draft=None, spec_k=4, spec_mode="auto",
                      spec_tree=None, sampling_epilogue="auto",
                      trace_ring=256, trace_log_path=None,
                      tenants_config=None, host_adapter_cache_mb=0.0):
    def _load():
        try:
            STATE.model_path = model_path
            batched = slots > 1 and not quantization
            # refusing beats silently serving the base model under a tenant's
            # adapter name / running a full-size cache the operator budgeted
            # HBM against
            for flag, val in (("--adapters", adapters),
                              ("--adapter_pool", adapter_pool),
                              ("--prefix_cache", prefix_cache),
                              ("--kv_quant", kv_quant),
                              ("--kv_block_size", kv_block_size),
                              ("--kv_overcommit", kv_overcommit == "on"),
                              # only "on" demands the batched paged engine;
                              # "off"/"auto" are no-ops everywhere else
                              ("--paged_kernel", paged_kernel == "on"),
                              ("--spec_draft_config", spec_draft),
                              ("--spec_tree", spec_tree),
                              # only "on" demands the batched engine; the
                              # single-slot path has no fused epilogue
                              ("--sampling_epilogue",
                               sampling_epilogue == "on"),
                              ("--tenants_config", tenants_config),
                              ("--host_adapter_cache_mb",
                               host_adapter_cache_mb)):
                if val and not batched:
                    raise ValueError(
                        f"{flag} requires the batched engine "
                        "(--slots > 1, no --quantization)"
                    )
            if batched:
                from datatunerx_tpu.serving.batched_engine import BatchedEngine

                STATE.engine = BatchedEngine(
                    model_path, checkpoint_path or None, adapters=adapters,
                    adapter_pool=adapter_pool,
                    adapter_rank_max=adapter_rank_max,
                    adapter_targets=adapter_targets or None,
                    template=template, max_seq_len=max_seq_len,
                    slots=slots, decode_chunk=decode_chunk,
                    kv_quant=kv_quant or None, prefix_cache=prefix_cache,
                    kv_block_size=kv_block_size, kv_blocks=kv_blocks or None,
                    kv_overcommit=kv_overcommit or "off",
                    paged_kernel=paged_kernel or "auto",
                    spec_draft=spec_draft or None,
                    spec_k=spec_k, spec_mode=spec_mode or "auto",
                    spec_tree=spec_tree or None,
                    sampling_epilogue=sampling_epilogue or "auto",
                    prefill_chunk=prefill_chunk,
                    prefill_token_budget=prefill_token_budget,
                    # the server's registry: engine TTFT/TPOT/prefill-chunk
                    # histograms land in the same /metrics exposition
                    registry=STATE.registry,
                    trace_ring=trace_ring,
                    trace_log_path=trace_log_path or None,
                    tenants=tenants_config or None,
                    host_adapter_cache_mb=host_adapter_cache_mb or 0.0,
                )
            else:
                # single-slot path also carries serve-time quantization
                from datatunerx_tpu.serving.engine import InferenceEngine

                STATE.engine = InferenceEngine(
                    model_path, checkpoint_path or None, template=template,
                    max_seq_len=max_seq_len, quantization=quantization or None,
                )
        except Exception as e:  # noqa: BLE001
            STATE.error = str(e)

    t = threading.Thread(target=_load, daemon=True)
    t.start()
    return t


def parse_adapters(spec: str) -> dict:
    """--adapters name=ckpt_path[,name=path…]"""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, path = part.partition("=")
        if not name or not path:
            raise ValueError(f"bad adapter spec {part!r}; want name=path")
        out[name] = path
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="datatunerx-tpu-serving")
    p.add_argument("--model_path", required=True)
    p.add_argument("--checkpoint_path", default="")
    p.add_argument("--template", default="llama2")
    p.add_argument("--max_seq_len", type=int, default=1024)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--quantization", default="",
                   choices=["", "int8", "int4", "nf4"],
                   help="serve-time base-weight quantization")
    p.add_argument("--slots", type=int, default=4,
                   help="continuous-batching cache slots (1 = single-request engine)")
    p.add_argument("--decode_chunk", type=int, default=8,
                   help="tokens per decode program (admission latency bound)")
    p.add_argument("--adapters", default="",
                   help="named LoRA adapters: name=ckpt[,name=ckpt…]; "
                        "requests select one via the 'model' field")
    p.add_argument("--adapter_pool", type=int, default=0,
                   help="dynamic multi-adapter pool: N HBM slots adapters "
                        "load into at runtime (load-on-miss, LRU evict, "
                        "POST/DELETE /admin/adapters); 0 = static "
                        "--adapters stack baked at startup")
    p.add_argument("--adapter_rank_max", type=int, default=8,
                   help="pool rank ceiling; lower ranks are zero-padded "
                        "(numerically invisible), higher ranks rejected")
    p.add_argument("--adapter_targets", default="",
                   help="pool LoRA target set, comma-separated (default "
                        "q_proj,v_proj); adapters training other targets "
                        "are rejected")
    p.add_argument("--kv_quant", default="", choices=["", "int8"],
                   help="int8-quantized KV cache: half the cache HBM, double "
                        "the slots×context budget (batched engine only)")
    p.add_argument("--prefix_cache", type=int, default=0,
                   help="LRU entries of reusable prefilled prompt prefixes "
                        "(shared system prompts / repeated probes skip "
                        "prefill; batched engine only; costs one cache row "
                        "of HBM per entry)")
    p.add_argument("--kv_block_size", type=int, default=0,
                   help="paged KV cache block size in tokens (0 = dense "
                        "slots×max_seq_len cache); admission reserves "
                        "blocks, not full-width rows — see README "
                        "'Serving performance' for the HBM math")
    p.add_argument("--kv_blocks", type=int, default=0,
                   help="total blocks in the paged pool (default "
                        "slots × max_seq_len / kv_block_size; set lower to "
                        "serve the same slots in less HBM)")
    p.add_argument("--kv_overcommit", default="off",
                   choices=["off", "on"],
                   help="on: KV overcommit — admission reserves only the "
                        "prompt's blocks plus a small headroom, the "
                        "scheduler grows tables at each cursor, prefix-"
                        "cache hits share refcounted blocks copy-on-write, "
                        "and exhaustion preempts youngest-first (sessions "
                        "park host-side and resume token-exactly). off "
                        "(default) = eager ceil((prompt+max_new)/bs) "
                        "reserve, byte-identical to the pre-overcommit "
                        "engine")
    p.add_argument("--paged_kernel", default="auto",
                   choices=["auto", "on", "off"],
                   help="Pallas in-place paged-attention decode kernel: "
                        "auto = kernel on TPU / XLA gather elsewhere, "
                        "on = force the kernel (interpret-mode on CPU), "
                        "off = always the gather oracle; needs "
                        "--kv_block_size > 0 to engage")
    p.add_argument("--spec_draft_config", default="",
                   help="speculative decoding draft model: a model path, "
                        "preset:<name> (same vocab as the target), or "
                        "take:N (self-speculative — the target's first N "
                        "layers). Empty = speculative decoding off")
    p.add_argument("--spec_k", type=int, default=4,
                   help="draft proposals per verify step (the adaptive "
                        "controller's ceiling)")
    p.add_argument("--spec_mode", default="auto",
                   choices=["auto", "on", "off"],
                   help="speculative decoding: auto = adaptive (shrink k / "
                        "fall back to plain decode when acceptance "
                        "collapses), on = always draft, off = exactly "
                        "today's decode path")
    p.add_argument("--spec_tree", default="",
                   help="tree-draft speculative verification: 'WxD' (branch "
                        "width x draft depth, e.g. 4x3) flattens a per-slot "
                        "token tree into one batched verify forward and "
                        "accepts the longest surviving root-to-leaf path. "
                        "Requires --spec_draft_config. Empty (default) = "
                        "chain drafts, byte-identical to before")
    p.add_argument("--sampling_epilogue", default="auto",
                   choices=["auto", "on", "off"],
                   help="fused on-chip sampling epilogue "
                        "(ops/pallas_sampling.py): decode/spec programs "
                        "sample inside the traced computation instead of "
                        "materializing [slots, vocab] logits for the host "
                        "sampler. auto = on for TPU backends, off "
                        "elsewhere; on = force anywhere (non-TPU runs use "
                        "the exact XLA oracle); off = legacy sampler, "
                        "programs byte-identical to before")
    p.add_argument("--prefill_chunk", type=int, default=256,
                   help="chunked-prefill program length in tokens (paged "
                        "engine); long prompts prefill in chunks "
                        "interleaved with decode")
    p.add_argument("--prefill_token_budget", type=int, default=0,
                   help="max prefill tokens the scheduler spends between "
                        "decode chunks (0 = unbounded); bounds the TPOT "
                        "hit a long admission can inflict on in-flight "
                        "requests")
    p.add_argument("--role", default="mixed",
                   choices=["prefill", "decode", "mixed"],
                   help="disaggregation role declared to the fleet: "
                        "prefill = long-prompt specialist (the gateway "
                        "steers prompts over its threshold here and the "
                        "handoff coordinator re-homes finished prefills "
                        "for decode), decode = token production, mixed "
                        "(default) = role-less, routing byte-identical "
                        "to older fleets")
    p.add_argument("--tenants_config", default="",
                   help="multi-tenant QoS directory: a JSON file path or "
                        "inline JSON object mapping tenant → {tier: "
                        "pinned|standard|bulk, adapters: [...], share, "
                        "kv_block_quota, ttft_p95_ms}. Empty (default) = "
                        "tenancy plane off, scheduling byte-identical")
    p.add_argument("--host_adapter_cache_mb", type=float, default=0.0,
                   help="host-RAM adapter tier budget in MB: evicted "
                        "adapters' host arrays stay cached so "
                        "evict→reload skips the orbax read; 0 (default) "
                        "= tier off")
    p.add_argument("--trace_ring", type=int, default=256,
                   help="completed request traces kept for "
                        "GET /debug/trace/<id>")
    p.add_argument("--trace_log", default="",
                   help="append every completed request span as one JSON "
                        "line to this file (offline trace forensics)")
    p.add_argument("--slo_config", default="",
                   help="JSON file of SLO specs (obs/slo.py format) judged "
                        "at GET /debug/slo; default: built-in serving "
                        "availability + TTFT objectives")
    p.add_argument("--slo_sample_s", type=float, default=15.0,
                   help="background SLO sampling interval (0 = sample only "
                        "on /debug/slo)")
    args = p.parse_args(argv)

    STATE.role = args.role
    if args.slo_config:
        from datatunerx_tpu.obs.slo import load_slos

        with STATE.slo_lock:
            STATE.slo = SLOEvaluator(STATE.registry,
                                     load_slos(args.slo_config))
    if args.slo_sample_s > 0:
        slo_evaluator().start(args.slo_sample_s)

    load_engine_async(args.model_path, args.checkpoint_path, args.template,
                      args.max_seq_len, quantization=args.quantization,
                      slots=args.slots, decode_chunk=args.decode_chunk,
                      adapters=parse_adapters(args.adapters),
                      adapter_pool=args.adapter_pool,
                      adapter_rank_max=args.adapter_rank_max,
                      adapter_targets=[t.strip() for t in
                                       args.adapter_targets.split(",")
                                       if t.strip()] or None,
                      kv_quant=args.kv_quant, prefix_cache=args.prefix_cache,
                      kv_block_size=args.kv_block_size,
                      kv_blocks=args.kv_blocks,
                      kv_overcommit=args.kv_overcommit,
                      prefill_chunk=args.prefill_chunk,
                      prefill_token_budget=args.prefill_token_budget,
                      paged_kernel=args.paged_kernel,
                      spec_draft=args.spec_draft_config,
                      spec_k=args.spec_k, spec_mode=args.spec_mode,
                      spec_tree=args.spec_tree,
                      sampling_epilogue=args.sampling_epilogue,
                      trace_ring=args.trace_ring,
                      trace_log_path=args.trace_log,
                      tenants_config=args.tenants_config,
                      host_adapter_cache_mb=args.host_adapter_cache_mb)
    srv = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    print(f"[serving] listening on :{args.port} (model loading async)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
