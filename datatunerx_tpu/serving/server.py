"""Serving HTTP server: OpenAI-ish ``/chat/completions`` + health gating.

Endpoint contract matches what the reference pipeline consumes
(reference finetunejob_controller.go:433 builds
``http://<svc>:8000/chat/completions``; the Scoring operator POSTs there).
Health semantics replace KubeRay's application-level HEALTHY gate
(finetunejob_controller.go:423-424): ``/healthz`` returns 503 until the model
is fully loaded, then 200 — so a k8s readinessProbe gives the same
"model actually loaded" guarantee.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ServingState:
    def __init__(self):
        self.engine = None
        self.error: Optional[str] = None
        self.model_path = ""


STATE = ServingState()


class Handler(BaseHTTPRequestHandler):
    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            if STATE.engine is not None:
                self._json(200, {"status": "HEALTHY", "model": STATE.model_path})
            elif STATE.error:
                self._json(500, {"status": "FAILED", "error": STATE.error})
            else:
                self._json(503, {"status": "LOADING"})
        elif self.path == "/v1/models":
            self._json(200, {"object": "list", "data": [
                {"id": STATE.model_path, "object": "model"}]})
        else:
            self._json(404, {"error": "not found"})

    def do_POST(self):
        if self.path not in ("/chat/completions", "/v1/chat/completions"):
            self._json(404, {"error": "not found"})
            return
        if STATE.engine is None:
            self._json(503, {"error": "model not loaded"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._json(400, {"error": f"invalid JSON body: {e}"})
                return
            messages = req.get("messages")
            if not isinstance(messages, list) or not messages:
                self._json(400, {"error": "messages must be a non-empty list"})
                return
            text = STATE.engine.chat(
                messages,
                max_new_tokens=int(req.get("max_tokens", 128)),
                temperature=float(req.get("temperature", 0.0)),
                top_p=float(req.get("top_p", 1.0)),
            )
            self._json(200, {
                "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": STATE.model_path,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }],
            })
        except Exception as e:  # noqa: BLE001 - serving must answer, not die
            self._json(500, {"error": str(e)})

    def log_message(self, *a):
        pass


def load_engine_async(model_path, checkpoint_path, template, max_seq_len,
                      quantization=None):
    def _load():
        try:
            from datatunerx_tpu.serving.engine import InferenceEngine

            STATE.model_path = model_path
            STATE.engine = InferenceEngine(
                model_path, checkpoint_path or None, template=template,
                max_seq_len=max_seq_len, quantization=quantization or None,
            )
        except Exception as e:  # noqa: BLE001
            STATE.error = str(e)

    t = threading.Thread(target=_load, daemon=True)
    t.start()
    return t


def main(argv=None):
    p = argparse.ArgumentParser(prog="datatunerx-tpu-serving")
    p.add_argument("--model_path", required=True)
    p.add_argument("--checkpoint_path", default="")
    p.add_argument("--template", default="llama2")
    p.add_argument("--max_seq_len", type=int, default=1024)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--quantization", default="",
                   choices=["", "int8", "int4", "nf4"],
                   help="serve-time base-weight quantization")
    args = p.parse_args(argv)

    load_engine_async(args.model_path, args.checkpoint_path, args.template,
                      args.max_seq_len, quantization=args.quantization)
    srv = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    print(f"[serving] listening on :{args.port} (model loading async)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
