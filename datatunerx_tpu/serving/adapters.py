"""Adapter-checkpoint utilities for multi-adapter serving.

``make_adapter_checkpoint`` synthesizes a LoRA adapter checkpoint with
random weights — the shape/layout of a real training run's Orbax state
(``{"lora": {"layers": ...}}``, loadable by
``batched_engine.load_checkpoint_state``) without paying for a training
run. Used by the side-by-side serving bench
(``scripts/bench_serving.py::bench_multi_adapter``, BASELINE row 6) and its
test (``tests/test_sidebyside_serving.py``); numerics are meaningless by
design — only routing, throughput, and isolation are measured.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from datatunerx_tpu.models import get_config
from datatunerx_tpu.models.lora import init_lora_params


def make_adapter_checkpoint(path: str, model: str, seed: int,
                            rank: int = 4,
                            targets=("q_proj", "v_proj")) -> str:
    """Write a synthetic LoRA adapter checkpoint under ``path`` and return
    it. Random A AND B (init's zero-B would make the adapter a no-op and
    every adapter identical)."""
    from datatunerx_tpu.training.checkpoint import CheckpointManager

    cfg = get_config(model.split(":")[-1])
    key = jax.random.PRNGKey(seed)
    lora = init_lora_params(cfg, key, rank=rank, targets=tuple(targets))
    layers = {}
    for i, (t, leaf) in enumerate(sorted(lora["layers"].items())):
        b = 0.05 * jax.random.normal(
            jax.random.fold_in(key, 1000 + i), leaf["b"].shape, jnp.float32)
        layers[t] = {"a": leaf["a"], "b": b}
    mngr = CheckpointManager(path)
    mngr.maybe_save({"lora": {"layers": layers}}, step=1, force=True)
    mngr.close()
    return path


def make_adapter_sweep(base_path: str, model: str, count: int,
                       ranks=(2, 4, 8), targets=("q_proj", "v_proj"),
                       seed: int = 0) -> dict:
    """``count`` synthetic adapters cycling through ``ranks`` — the
    mixed-rank tenant population the pooled AdapterStore rank-pads (tests)
    and the adapter-churn serve bench rotates through. Returns
    {name: checkpoint_path}; names are ``ad<i>-r<rank>`` so a failure
    message states the rank that produced it."""
    import os

    out = {}
    for i in range(count):
        rank = ranks[i % len(ranks)]
        name = f"ad{i}-r{rank}"
        out[name] = make_adapter_checkpoint(
            os.path.join(base_path, name), model, seed=seed + i,
            rank=rank, targets=targets)
    return out
