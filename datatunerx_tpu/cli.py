"""dtx — the operator CLI (reference ecosystem's ``dtx-ctl``, SURVEY.md §1,
INSTALL.md:26-48 — install/apply/inspect instead of Helm+kubectl).

Talks to the operator's REST API (operator/apiserver.py):

  dtx apply -f resources.json|yaml     create/update CRs (accepts a single
                                       object or a list; JSON, or YAML if
                                       pyyaml is available)
  dtx get <kind> [name] [-n ns] [-o json]
  dtx delete <kind> <name> [-n ns]
  dtx status <finetunejob-name>        condensed pipeline view
  dtx logs <finetune-name>             trainer log tail (local backend)
  dtx install [--kube-url URL]         one-command install: CRDs + RBAC +
                                       operator Deployment + config
                                       (env → ConfigMap/Secret); --dry-run
                                       prints the manifests instead
  dtx serve --model_path P             serve directly (no operator); with
      [--replicas N] [--gateway]       N > 1 or --gateway the inference
                                       gateway fronts the replicas
  dtx experiment -f spec.json          run a closed-loop experiment locally
      [--backend fake|local]           (shared slice pool, continuous
                                       scoring, canary promotion) against
                                       the Fake or LocalProcess backends
  dtx lint [paths...]                  JAX-aware static analysis (dtxlint):
                                       host-sync, retrace, sharding, and
                                       lock-discipline rules; exits 1 on
                                       findings (the tier-1 CI gate)
  dtx replay [--url U | --selftest]    trace-driven load replay + chaos
                                       harness (loadgen/): heavy-tail
                                       multi-turn adapter-churning traffic,
                                       fault injection over the admin
                                       surfaces, SLO epilogue that exits
                                       nonzero naming violated objectives;
                                       --from_trace_log converts a gateway
                                       --trace_log into a replayable
                                       dtx-load-trace (real traffic shape),
                                       --expect_handoff asserts a mid-
                                       stream drain dropped nothing

Server address from --server or DTX_SERVER (default http://127.0.0.1:8080);
bearer auth via DTX_API_TOKEN when the server requires it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

_GROUP_BY_KIND = {
    "Finetune": "finetune.datatunerx.io",
    "FinetuneJob": "finetune.datatunerx.io",
    "FinetuneExperiment": "finetune.datatunerx.io",
    "LLM": "core.datatunerx.io",
    "Hyperparameter": "core.datatunerx.io",
    "LLMCheckpoint": "core.datatunerx.io",
    "Dataset": "extension.datatunerx.io",
    "Scoring": "extension.datatunerx.io",
}
_KIND_ALIASES = {k.lower(): k for k in _GROUP_BY_KIND}
_KIND_ALIASES.update({k.lower() + "s": k for k in _GROUP_BY_KIND})
_KIND_ALIASES.update({"ftj": "FinetuneJob", "ftexp": "FinetuneExperiment",
                      "ft": "Finetune", "hp": "Hyperparameter", "ds": "Dataset"})


def _kind(raw: str) -> str:
    k = _KIND_ALIASES.get(raw.lower())
    if not k:
        sys.exit(f"error: unknown kind {raw!r}; one of {sorted(_GROUP_BY_KIND)}")
    return k


def _url(server: str, kind: str, ns: str = None, name: str = None) -> str:
    group = _GROUP_BY_KIND[kind]
    url = f"{server}/apis/{group}/v1beta1/{kind.lower()}"
    if ns:
        url += f"/{ns}"
        if name:
            url += f"/{name}"
    return url


def _request(method: str, url: str, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"}
    if os.environ.get("DTX_API_TOKEN"):
        headers["Authorization"] = f"Bearer {os.environ['DTX_API_TOKEN']}"
    req = urllib.request.Request(url, data=data, method=method, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.load(e)
        except Exception:
            return e.code, {"error": e.reason}
    except urllib.error.URLError as e:
        sys.exit(f"error: cannot reach API server at {url.split('/apis')[0]}: {e.reason}")


def _load_docs(path: str):
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml  # optional

            docs = [d for d in yaml.safe_load_all(text) if d]
        except ImportError:
            sys.exit("error: pyyaml not available; use JSON manifests")
    else:
        loaded = json.loads(text)
        docs = loaded if isinstance(loaded, list) else [loaded]
    return docs


def cmd_apply(args):
    for doc in _load_docs(args.filename):
        kind = _kind(doc.get("kind", ""))
        meta = doc.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name")
        code, resp = _request("POST", _url(args.server, kind), doc)
        if code == 409:  # exists → fetch rv and update
            code_get, current = _request("GET", _url(args.server, kind, ns, name))
            if code_get == 200:
                doc.setdefault("metadata", {})["resource_version"] = (
                    current["metadata"]["resource_version"]
                )
                doc["metadata"]["uid"] = current["metadata"]["uid"]
                code, resp = _request("PUT", _url(args.server, kind, ns, name), doc)
        if code in (200, 201):
            print(f"{kind}/{name} {'created' if code == 201 else 'configured'}")
        else:
            sys.exit(f"error applying {kind}/{name}: {resp.get('error', resp)}")


def cmd_get(args):
    kind = _kind(args.kind)
    if args.name:
        code, resp = _request("GET", _url(args.server, kind, args.namespace, args.name))
        if code != 200:
            sys.exit(f"error: {resp.get('error')}")
        if args.output == "json":
            print(json.dumps(resp, indent=1, default=str))
        else:
            _print_table(kind, [resp])
        return
    code, resp = _request("GET", _url(args.server, kind) + f"/{args.namespace}")
    if code != 200:
        sys.exit(f"error: {resp.get('error')}")
    if args.output == "json":
        print(json.dumps(resp, indent=1, default=str))
    else:
        _print_table(kind, resp.get("items", []))


def _print_table(kind, items):
    rows = []
    for it in items:
        meta, status = it.get("metadata", {}), it.get("status", {})
        state = status.get("state", "")
        extra = ""
        if kind == "FinetuneJob":
            extra = str(status.get("result", {}).get("score", ""))
        elif kind == "FinetuneExperiment":
            extra = str(status.get("bestVersion", {}).get("score", ""))
        elif kind == "Scoring":
            state = ""
            extra = str(status.get("score", ""))
        rows.append((meta.get("name", ""), state, extra))
    name_w = max([4] + [len(r[0]) for r in rows]) + 2
    state_w = max([5] + [len(r[1]) for r in rows]) + 2
    print(f"{'NAME':<{name_w}}{'STATE':<{state_w}}SCORE")
    for name, state, extra in rows:
        print(f"{name:<{name_w}}{state:<{state_w}}{extra}")


def cmd_delete(args):
    kind = _kind(args.kind)
    code, resp = _request("DELETE", _url(args.server, kind, args.namespace, args.name))
    if code != 200:
        sys.exit(f"error: {resp.get('error')}")
    print(f"{kind}/{args.name} deleted")


def cmd_status(args):
    code, job = _request(
        "GET", _url(args.server, "FinetuneJob", args.namespace, args.name))
    if code != 200:
        sys.exit(f"error: {job.get('error')}")
    status = job.get("status", {})
    result = status.get("result", {})
    print(f"FinetuneJob {args.name}")
    print(f"  state:      {status.get('state', '')}")
    print(f"  finetune:   {status.get('finetuneStatus', {}).get('state', '')}")
    print(f"  serve:      {result.get('serve', '')}")
    print(f"  score:      {result.get('score', '')}")
    print(f"  checkpoint: {result.get('checkpointPath', '')}")


def cmd_logs(args):
    code, resp = _request("GET", f"{args.server}/logs/{args.namespace}/{args.name}")
    if code != 200:
        sys.exit(f"error: {resp.get('error')}")
    print(resp.get("log", ""), end="")


def cmd_serve(args):
    """Launch serving directly (no operator): a single serving.server, or —
    with --replicas N / --gateway — the inference gateway fronting N replica
    subprocesses (routing, admission control, failover; gateway/server.py)."""
    if args.replicas > 1 or args.gateway:
        from datatunerx_tpu.gateway.server import main as gateway_main

        argv = [
            "--model_path", args.model_path,
            "--checkpoint_path", args.checkpoint_path,
            "--template", args.template,
            "--max_seq_len", str(args.max_seq_len),
            "--port", str(args.port),
            "--quantization", args.quantization,
            "--slots", str(args.slots),
            "--adapters", args.adapters,
            "--adapter_pool", str(args.adapter_pool),
            "--adapter_rank_max", str(args.adapter_rank_max),
            "--kv_block_size", str(args.kv_block_size),
            "--kv_blocks", str(args.kv_blocks),
            "--kv_overcommit", args.kv_overcommit,
            "--paged_kernel", args.paged_kernel,
            "--spec_draft_config", args.spec_draft_config,
            "--spec_k", str(args.spec_k),
            "--spec_mode", args.spec_mode,
            "--spec_tree", args.spec_tree,
            "--sampling_epilogue", args.sampling_epilogue,
            "--prefill_token_budget", str(args.prefill_token_budget),
            "--replicas", str(max(args.replicas, 1)),
            "--policy", args.policy,
            "--max_queue", str(args.max_queue),
            "--token_budget", str(args.token_budget),
            "--role", args.role,
            "--prefill_threshold", str(args.prefill_threshold),
            "--fleet_prefix_mb", str(args.fleet_prefix_mb),
            "--fleet_handoff", str(int(args.fleet_handoff)),
            "--fleet_spill", str(int(args.fleet_spill)),
            "--tenants_config", args.tenants_config,
            "--host_adapter_cache_mb", str(args.host_adapter_cache_mb),
        ]
        if args.workdir:
            argv += ["--workdir", args.workdir]
        return gateway_main(argv)
    from datatunerx_tpu.serving.server import main as serving_main

    argv = [
        "--model_path", args.model_path,
        "--checkpoint_path", args.checkpoint_path,
        "--template", args.template,
        "--max_seq_len", str(args.max_seq_len),
        "--port", str(args.port),
        "--quantization", args.quantization,
        "--slots", str(args.slots),
        "--adapters", args.adapters,
        "--adapter_pool", str(args.adapter_pool),
        "--adapter_rank_max", str(args.adapter_rank_max),
        "--kv_block_size", str(args.kv_block_size),
        "--kv_blocks", str(args.kv_blocks),
        "--kv_overcommit", args.kv_overcommit,
        "--paged_kernel", args.paged_kernel,
        "--spec_draft_config", args.spec_draft_config,
        "--spec_k", str(args.spec_k),
        "--spec_mode", args.spec_mode,
        "--spec_tree", args.spec_tree,
        "--sampling_epilogue", args.sampling_epilogue,
        "--prefill_token_budget", str(args.prefill_token_budget),
        "--tenants_config", args.tenants_config,
        "--host_adapter_cache_mb", str(args.host_adapter_cache_mb),
    ]
    if args.role:
        # single server: one role, not a cycle (serving.server validates)
        argv += ["--role", args.role]
    return serving_main(argv)


def cmd_experiment(args):
    """Run a closed-loop experiment (experiment/runner.py): N jobs
    elastically scheduled on a shared slice pool, continuous scoring into
    a live leaderboard, winner promoted through canary traffic weights."""
    from datatunerx_tpu.experiment.runner import main as experiment_main

    argv = ["-f", args.filename, "--backend", args.backend,
            "--workdir", args.workdir,
            "--max_ticks", str(args.max_ticks),
            "--tick_s", str(args.tick_s)]
    if args.status_json:
        argv += ["--status_json", args.status_json]
    return experiment_main(argv)


def _passthrough_tail(argv, cmd):
    """The argv tail after ``cmd`` when it is the subcommand — allowing
    the one global option (``--server``) before it — else None. Both
    ``lint`` (dtxlint) and ``replay`` (loadgen) own their full flag
    surface, so they must bypass dtx's argparse entirely: a REMAINDER
    positional drops leading optionals like ``--format``/``--url``, so
    these subcommands dispatch before parsing."""
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok == "--server":
            i += 2
            continue
        if tok.startswith("--server="):
            i += 1
            continue
        return argv[i + 1:] if tok == cmd else None
    return None


def cmd_lint(args):
    # unreachable in practice — main() intercepts every lint invocation
    # before argparse — kept so the help-listing subparser has an action
    from datatunerx_tpu.analysis.cli import main as lint_main

    return lint_main([])


def cmd_replay(args):
    # unreachable like cmd_lint — main() dispatches replay before argparse
    from datatunerx_tpu.loadgen.replay import main as replay_main

    return replay_main([])


def cmd_san(args):
    # unreachable like cmd_lint — main() dispatches san before argparse
    from datatunerx_tpu.analysis.sanitizers.cli import main as san_main

    return san_main([])


def cmd_install(args):
    """One-command install (reference dtx-ctl + Helm, INSTALL.md:26-48)."""
    from datatunerx_tpu.operator.install import install, render_install_manifests

    env = {}
    for item in args.set or []:
        key, sep, val = item.partition("=")
        if not sep:
            sys.exit(f"error: --set expects KEY=VALUE, got {item!r}")
        env[key] = val

    kw = dict(
        namespace=args.namespace,
        image=args.image,
        env=env,
        storage_path=args.storage_path,
        leader_elect=args.leader_elect,
        replicas=args.replicas,
        include_webhooks=not args.no_webhooks,
    )
    if args.dry_run:
        docs = render_install_manifests(**kw)
        try:
            import yaml

            print(yaml.safe_dump_all(docs, sort_keys=False), end="")
        except ImportError:
            print(json.dumps(docs, indent=1))
        return
    from datatunerx_tpu.operator.kubeclient import KubeClient

    client = KubeClient(base_url=args.kube_url,
                        namespace=args.namespace)
    ns = kw.pop("namespace")
    for line in install(client, namespace=ns, **kw):
        print(line)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    lint_tail = _passthrough_tail(argv, "lint")
    if lint_tail is not None:
        from datatunerx_tpu.analysis.cli import main as lint_main

        return lint_main(lint_tail)
    replay_tail = _passthrough_tail(argv, "replay")
    if replay_tail is not None:
        from datatunerx_tpu.loadgen.replay import main as replay_main

        return replay_main(replay_tail)
    san_tail = _passthrough_tail(argv, "san")
    if san_tail is not None:
        from datatunerx_tpu.analysis.sanitizers.cli import main as san_main

        return san_main(san_tail)
    p = argparse.ArgumentParser(prog="dtx")
    p.add_argument("--server", default=os.environ.get("DTX_SERVER",
                                                      "http://127.0.0.1:8080"))
    sub = p.add_subparsers(dest="cmd", required=True)

    ap = sub.add_parser("apply")
    ap.add_argument("-f", "--filename", required=True)
    ap.set_defaults(fn=cmd_apply)

    gp = sub.add_parser("get")
    gp.add_argument("kind")
    gp.add_argument("name", nargs="?")
    gp.add_argument("-n", "--namespace", default="default")
    gp.add_argument("-o", "--output", choices=["table", "json"], default="table")
    gp.set_defaults(fn=cmd_get)

    dp = sub.add_parser("delete")
    dp.add_argument("kind")
    dp.add_argument("name")
    dp.add_argument("-n", "--namespace", default="default")
    dp.set_defaults(fn=cmd_delete)

    sp = sub.add_parser("status")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.set_defaults(fn=cmd_status)

    lp = sub.add_parser("logs")
    lp.add_argument("name")
    lp.add_argument("-n", "--namespace", default="default")
    lp.set_defaults(fn=cmd_logs)

    vp = sub.add_parser(
        "serve",
        help="serve a model directly: single server, or --replicas N / "
             "--gateway for the multi-replica inference gateway")
    vp.add_argument("--model_path", required=True)
    vp.add_argument("--checkpoint_path", default="")
    vp.add_argument("--template", default="llama2")
    vp.add_argument("--max_seq_len", type=int, default=1024)
    vp.add_argument("--port", type=int, default=8000)
    vp.add_argument("--quantization", default="",
                    choices=["", "int8", "int4", "nf4"])
    vp.add_argument("--slots", type=int, default=4)
    vp.add_argument("--adapters", default="",
                    help="named LoRA adapters: name=ckpt[,name=ckpt…]")
    vp.add_argument("--adapter_pool", type=int, default=0,
                    help="dynamic multi-adapter pool: N HBM slots adapters "
                         "load into at runtime (load-on-miss + LRU evict "
                         "via POST/DELETE /admin/adapters; 0 = static "
                         "--adapters stack)")
    vp.add_argument("--adapter_rank_max", type=int, default=8,
                    help="pool rank ceiling; lower ranks are zero-padded, "
                         "higher ranks rejected")
    vp.add_argument("--kv_block_size", type=int, default=0,
                    help="paged KV cache block size in tokens (0 = dense)")
    vp.add_argument("--kv_blocks", type=int, default=0,
                    help="paged pool size in blocks (default: dense parity)")
    vp.add_argument("--kv_overcommit", default="off",
                    choices=["off", "on"],
                    help="on: lazy block reserve + on-demand growth + COW "
                         "prefix blocks + youngest-first preemption (more "
                         "concurrent sessions per chip); off = eager "
                         "reserve, byte-identical to the classic engine")
    vp.add_argument("--paged_kernel", default="auto",
                    choices=["auto", "on", "off"],
                    help="Pallas in-place paged decode kernel: auto = "
                         "kernel on TPU / gather elsewhere, on = force "
                         "(interpret-mode on CPU), off = gather oracle")
    vp.add_argument("--spec_draft_config", default="",
                    help="speculative decoding draft: model path, "
                         "preset:<name>, or take:N (target's first N "
                         "layers); empty = off")
    vp.add_argument("--spec_k", type=int, default=4,
                    help="draft proposals per verify step")
    vp.add_argument("--spec_mode", default="auto",
                    choices=["auto", "on", "off"],
                    help="speculative decoding: auto = adaptive, on = "
                         "pinned, off = plain decode")
    vp.add_argument("--spec_tree", default="",
                    help="tree drafts 'WxD' (width x depth, e.g. 4x3): one "
                         "batched verify over W branches, accept the "
                         "longest surviving path; needs "
                         "--spec_draft_config; empty = chain drafts")
    vp.add_argument("--sampling_epilogue", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused on-chip sampling epilogue: auto = on for "
                         "TPU backends, on = force anywhere (exact XLA "
                         "oracle off-TPU), off = legacy host sampler")
    vp.add_argument("--prefill_token_budget", type=int, default=0,
                    help="prefill tokens per scheduler tick between decode "
                         "chunks (0 = unbounded)")
    vp.add_argument("--role", default="",
                    help="disaggregation role(s): a single role for one "
                         "server (prefill/decode/mixed), or a comma-"
                         "separated cycle for gateway-spawned replicas "
                         "(e.g. 'prefill,decode'); empty = all mixed")
    vp.add_argument("--prefill_threshold", type=int, default=0,
                    help="gateway: prompts of >= this many tokens prefer "
                         "role=prefill replicas (0 = role-blind routing)")
    vp.add_argument("--fleet_prefix_mb", type=float, default=0.0,
                    help="gateway: fleet-shared prefix tier budget in MB "
                         "(0 = off)")
    vp.add_argument("--fleet_handoff", type=int, default=0,
                    help="gateway: 1 = prefill→decode session handoff")
    vp.add_argument("--fleet_spill", type=int, default=0,
                    help="gateway: 1 = spill preemption-parked sessions "
                         "to peers with free KV blocks")
    vp.add_argument("--tenants_config", default="",
                    help="tenant directory (JSON file path or inline JSON "
                         "object): enables the multi-tenant QoS plane — "
                         "pinned/standard/bulk tiers, weighted-fair "
                         "admission shares, per-tenant KV block quotas "
                         "(empty = plane off, byte-identical serving)")
    vp.add_argument("--host_adapter_cache_mb", type=float, default=0.0,
                    help="host-RAM adapter tier budget in MB: evicted "
                         "pool adapters reload from host arrays instead "
                         "of orbax (0 = off)")
    vp.add_argument("--replicas", type=int, default=1,
                    help="replica count; > 1 puts the gateway in front")
    vp.add_argument("--gateway", action="store_true",
                    help="front even a single replica with the gateway "
                         "(admission control + metrics + rolling restart)")
    vp.add_argument("--policy", default="least_busy",
                    choices=["least_busy", "round_robin"])
    vp.add_argument("--max_queue", type=int, default=64)
    vp.add_argument("--token_budget", type=int, default=32768)
    vp.add_argument("--workdir", default="",
                    help="gateway replica log directory")
    vp.set_defaults(fn=cmd_serve)

    ep = sub.add_parser(
        "experiment",
        help="run a closed-loop experiment: shared slice pool, continuous "
             "scoring, canary promotion (experiment/)")
    ep.add_argument("-f", "--filename", required=True,
                    help="experiment spec JSON")
    ep.add_argument("--backend", choices=["fake", "local"], default="fake")
    ep.add_argument("--workdir", default="experiment-jobs")
    ep.add_argument("--max_ticks", type=int, default=2000)
    ep.add_argument("--tick_s", type=float, default=0.05)
    ep.add_argument("--status_json", default="")
    ep.set_defaults(fn=cmd_experiment)

    xp = sub.add_parser(
        "lint",
        help="JAX-aware static analysis (dtxlint); args pass through",
        add_help=False)
    xp.set_defaults(fn=cmd_lint)

    rp = sub.add_parser(
        "replay",
        help="trace-driven load replay + chaos harness with SLO verdict "
             "(loadgen/); args pass through",
        add_help=False)
    rp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser(
        "san",
        help="runtime sanitizer run (lock-order / thread-leak / compile "
             "budgets) over pytest; args pass through",
        add_help=False)
    sp.set_defaults(fn=cmd_san)

    ip = sub.add_parser(
        "install",
        help="install CRDs + RBAC + operator Deployment + config "
             "(reference dtx-ctl/Helm flow, INSTALL.md:26-48)")
    ip.add_argument("-n", "--namespace", default="datatunerx-dev")
    ip.add_argument("--image", default="datatunerx-tpu/operator:latest")
    ip.add_argument("--storage-path", default="/storage")
    ip.add_argument("--set", action="append", metavar="KEY=VALUE",
                    help="operator env config; credential keys "
                         "(S3_ACCESS_KEY, S3_SECRET_KEY, REGISTRY_USER, "
                         "REGISTRY_PASSWORD) land in a Secret, the rest in "
                         "a ConfigMap")
    ip.add_argument("--leader-elect", action="store_true")
    ip.add_argument("--replicas", type=int, default=1)
    ip.add_argument("--no-webhooks", action="store_true",
                    help="skip the admission webhook Service + configurations")
    ip.add_argument("--dry-run", action="store_true",
                    help="print the manifests instead of applying")
    ip.add_argument("--kube-url", default=os.environ.get("DTX_KUBE_URL"),
                    help="apiserver base URL (default: in-cluster config)")
    ip.set_defaults(fn=cmd_install)

    args = p.parse_args(argv)
    rc = args.fn(args)
    return int(rc) if isinstance(rc, int) else 0


if __name__ == "__main__":
    sys.exit(main())
