"""datatunerx-tpu: a TPU-native rebuild of DataTunerX (reference: /root/reference).

Capability surface (SURVEY.md §0): dataset registration → hyperparameter groups →
distributed LoRA/full SFT → checkpoint capture → serving → automatic scoring →
best-model selection across batch experiments.

Mechanism replacements (SURVEY.md §7.1): the reference's Ray Train/torch-DDP/NCCL
GPU path (reference cmd/tuning/train.py) becomes a single-program JAX/GSPMD trainer
over a `jax.sharding.Mesh`; bitsandbytes CUDA kernels become Pallas int8/int4
kernels; the Go/KubeRay control plane becomes a Python reconciler framework with
pluggable cluster backends.
"""

__version__ = "0.23.0"
