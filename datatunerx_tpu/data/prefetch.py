"""Pipelined training input path: host prefetch + double-buffered device placement.

The synchronous loop serializes three phases per step — host batch build
(tokenize/pack in ``data/loader.py``), host→device transfer (``device_put``),
and the step computation — so the accelerator idles on input between steps.
Production JAX trainers (MaxText's multihost dataloading; the tf.data pipeline
design of Murray et al., 2021) overlap all three. This module provides the same
overlap in three small pieces:

  HostPrefetcher    — runs any batch iterator in a background thread behind a
                      bounded queue (backpressure, exception propagation, clean
                      shutdown), so step N's host build happens during step N-1's
                      compute.
  DevicePrefetcher  — places batch N+1 onto the mesh while step N executes,
                      keeping ``depth`` batches in flight. Placement goes
                      through the SAME ``place_batch`` the Trainer uses inline
                      (parallel/sharding.py), so single- and multi-host paths
                      stay identical. Placed batches are marked ``PlacedBatch``
                      so ``Trainer.train_step``/``eval_step`` skip re-placing.
  MetricsBuffer     — holds in-flight device metrics and resolves only
                      completed ones (one logging interval behind), so a
                      logging boundary never drains the dispatch pipeline with
                      per-metric ``float(v)`` blocking calls.

Pipeline health (queue depth, host-build ms, device-put ms, step-wait ms) is
aggregated by ``PipelineStats`` and surfaces both in MetricsLogger records and
— via ``jax.profiler.TraceAnnotation`` around the host build and the device
put — in XProf traces, so the overlap (or its absence) is visible.

Determinism: the pipeline only changes WHEN work happens, never what the
batches contain or the order they arrive — the pipelined loop is loss-identical
to the synchronous loop on a fixed seed (tests/test_prefetch.py asserts the
exact loss sequence).
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

_ITEM, _ERROR, _DONE = 0, 1, 2


class PlacedBatch(dict):
    """A batch dict already placed on devices — ``Trainer._put_batch`` passes
    it through untouched instead of re-placing (which on multi-host would
    misread device arrays as process-local slices)."""


class PipelineStats:
    """Thread-safe accumulators for pipeline health, drained at logging
    boundaries. All times in milliseconds; ``snapshot()`` returns the means
    since the previous snapshot (so each logged record covers its interval).

    Accumulators are bounded (last ``maxlen`` samples per key): non-main
    processes in a multi-host run record every batch but never snapshot —
    unbounded lists would leak for the process lifetime. A logging interval
    longer than ``maxlen`` steps means the mean covers the interval's tail,
    which is the operative signal anyway."""

    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._maxlen = maxlen
        self._acc: Dict[str, collections.deque] = {}

    def record(self, key: str, value: float):
        with self._lock:
            dq = self._acc.get(key)
            if dq is None:
                dq = self._acc[key] = collections.deque(maxlen=self._maxlen)
            dq.append(float(value))

    def snapshot(self, reset: bool = True) -> Dict[str, float]:
        """Mean per key since the last snapshot, prefixed ``pipe_``."""
        with self._lock:
            out = {
                f"pipe_{k}": sum(v) / len(v) for k, v in self._acc.items() if v
            }
            if reset:
                self._acc.clear()
        return out


class ReadAheadIterator:
    """Bounded background read-ahead over a record iterable — the raw-fetch
    half of the streaming input path, split off from encoding.

    A ``gs://`` line stream pays its network latency inside ``readline``,
    which previously ran inline with tokenizer encoding on the
    HostPrefetcher's worker: one slow object-store read stalled batch
    assembly and, ``depth`` batches later, the train step. Here a reader
    thread pulls RAW records into a bounded queue while the consumer
    encodes, so network jitter overlaps encode/assembly instead of adding
    to it. Single producer + FIFO queue → order (and therefore batch
    content) is byte-identical to the synchronous path; source exceptions
    re-raise at the consumer. ``close()`` (or early generator exit)
    stops the reader promptly — it never blocks forever on a full queue.
    """

    _DONE = object()

    def __init__(self, records: Iterable, depth: int = 64):
        if depth < 1:
            raise ValueError(f"read-ahead depth must be >= 1, got {depth}")
        self._records = records
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="dtx-readahead")
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for rec in self._records:
                if not self._put(rec):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            self._exc = e
        self._put(self._DONE)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty():
                    # reader died without posting DONE (should not happen;
                    # belt-and-braces against a silent thread loss)
                    if self._exc is not None:
                        raise self._exc
                    raise StopIteration
                continue
            if item is self._DONE:
                if self._exc is not None:
                    raise self._exc
                raise StopIteration
            return item

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HostPrefetcher:
    """Runs a batch-producing iterator in a daemon thread behind a bounded
    queue.

    - ``source``: an iterable, or a zero-arg callable returning an iterator
      (use a callable when construction itself is expensive — e.g. re-opening
      a shuffle-buffered stream — so it also runs off the step loop's thread).
    - ``depth``: max batches buffered; the worker blocks (backpressure) once
      the queue is full, bounding host memory at ``depth`` batches.
    - A worker exception is re-raised in the consumer thread at the point the
      failed batch would have been consumed.
    - ``close()`` stops the worker promptly even when it is blocked on a full
      queue, and joins it; also invoked by ``__exit__`` and iterator exhaustion.
    """

    def __init__(
        self,
        source: Iterable | Callable[[], Iterator],
        depth: int = 2,
        stats: Optional[PipelineStats] = None,
        name: str = "dtx-host-prefetch",
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._source = source
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._stats = stats
        self._finished = False
        self._thread = threading.Thread(target=self._worker, name=name,
                                        daemon=True)
        self._thread.start()

    def resize(self, depth: int) -> int:
        """Grow (or shrink) the bounded queue LIVE — the in-run half of the
        prefetch advisory: when pipe_step_wait_ms says the step loop starves,
        the running prefetcher deepens without restarting the epoch. Queue
        mutation under the queue's own mutex; a worker blocked on put() is
        woken by not_full so new headroom is used immediately. Shrinking
        never drops batches — the queue just stops refilling until it
        drains below the new bound."""
        depth = int(depth)
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        with self._q.mutex:
            self._q.maxsize = depth
            self._q.not_full.notify_all()
        return depth

    # ------------------------------------------------------------- worker
    def _put(self, item) -> bool:
        """Queue-put that stays responsive to close(); False = shutting down."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            import jax

            src = self._source() if callable(self._source) else self._source
            it = iter(src)
            while not self._stop.is_set():
                t0 = time.perf_counter()
                with jax.profiler.TraceAnnotation("dtx_host_prefetch_build"):
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                build_ms = (time.perf_counter() - t0) * 1e3
                if self._stats is not None:
                    self._stats.record("host_build_ms", build_ms)
                    self._stats.record("queue_depth", self._q.qsize())
                if not self._put((_ITEM, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — propagated to consumer
            self._put((_ERROR, e))
            return
        self._put((_DONE, None))

    # ----------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                kind, payload = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                continue
        if self._stats is not None:
            self._stats.record("step_wait_ms", (time.perf_counter() - t0) * 1e3)
        if kind == _ERROR:
            self._finished = True
            self._thread.join(timeout=5)
            raise payload
        if kind == _DONE:
            self._finished = True
            self._thread.join(timeout=5)
            raise StopIteration
        return payload

    def close(self):
        """Stop the worker and drop buffered batches. Idempotent. A worker
        stuck inside ``next(source)`` (e.g. a blocking read) can't observe the
        stop event; the short join timeout leaves it to die with the process
        (daemon) rather than hanging shutdown."""
        self._stop.set()
        # drain so a worker blocked on put() can observe the stop event
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=1.0)
        self._finished = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class DevicePrefetcher:
    """Keeps up to ``depth`` device-placed batches in flight ahead of the
    consumer.

    ``place_fn`` is the host→device placement (typically
    ``lambda b: place_batch(b, mesh, accum=...)`` — parallel/sharding.py).
    ``device_put`` dispatches asynchronously on TPU, so placing batch N+1
    here overlaps its transfer with step N's compute; the step loop then
    receives ``PlacedBatch`` objects the Trainer consumes without a second
    placement. depth=2 is double buffering; 3 tolerates jittery host builds.
    """

    def __init__(
        self,
        host_batches: Iterable,
        place_fn: Callable[[Dict[str, Any]], Dict[str, Any]],
        depth: int = 2,
        stats: Optional[PipelineStats] = None,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._it = iter(host_batches)
        self._place = place_fn
        self._buf: collections.deque = collections.deque()
        self._depth = depth
        self._stats = stats
        self._exhausted = False

    def _fill(self):
        import jax

        while not self._exhausted and len(self._buf) < self._depth:
            try:
                hb = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation("dtx_device_prefetch_put"):
                placed = PlacedBatch(self._place(hb))
            if self._stats is not None:
                self._stats.record(
                    "device_put_ms", (time.perf_counter() - t0) * 1e3)
            self._buf.append(placed)

    def __iter__(self):
        return self

    def __next__(self):
        self._fill()
        if not self._buf:
            raise StopIteration
        return self._buf.popleft()


def prefetch_batches(
    source: Iterable | Callable[[], Iterator],
    place_fn: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None,
    depth: int = 2,
    stats: Optional[PipelineStats] = None,
) -> Tuple[Iterator, HostPrefetcher]:
    """Compose the full pipeline over one epoch's batches.

    Returns ``(iterator, host_prefetcher)`` — iterate the first; close the
    second when leaving the epoch early (break/exception) so the worker
    thread never outlives the loop.
    """
    host = HostPrefetcher(source, depth=depth, stats=stats)
    if place_fn is None:
        return host, host
    return DevicePrefetcher(host, place_fn, depth=depth, stats=stats), host


class MetricsBuffer:
    """Holds in-flight step metrics; resolves only completed ones.

    ``push`` stores the device arrays (no sync). ``pop_ready`` resolves every
    entry except the newest ``lag`` — by the next logging boundary those older
    steps' results have long been computed, so ``float(v)`` returns without
    draining dispatch — plus any newer entry whose arrays all report ready.
    ``drain`` resolves everything (end of training).
    """

    def __init__(self, lag: int = 1):
        self.lag = max(0, lag)
        self._pending: collections.deque = collections.deque()

    def __len__(self):
        return len(self._pending)

    def push(self, step: int, metrics: Dict[str, Any],
             extra: Optional[Dict[str, float]] = None):
        self._pending.append((step, metrics, extra or {}))

    @staticmethod
    def _ready(metrics: Dict[str, Any]) -> bool:
        for v in metrics.values():
            is_ready = getattr(v, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
        return True

    @staticmethod
    def _resolve(step, metrics, extra) -> Tuple[int, Dict[str, float]]:
        host = {k: float(v) for k, v in metrics.items()}
        host.update(extra)
        return step, host

    def pop_ready(self) -> List[Tuple[int, Dict[str, float]]]:
        out = []
        while len(self._pending) > self.lag:
            out.append(self._resolve(*self._pending.popleft()))
        while self._pending and self._ready(self._pending[0][1]):
            out.append(self._resolve(*self._pending.popleft()))
        return out

    def drain(self) -> List[Tuple[int, Dict[str, float]]]:
        out = [self._resolve(*entry) for entry in self._pending]
        self._pending.clear()
        return out
