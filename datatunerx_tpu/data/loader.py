"""Dataset ingest + deterministic batch iteration.

Replaces Ray Data CSV ingest (reference cmd/tuning/train.py:329-351: read_csv +
rename_columns + streaming split across workers). TPU-native: a plain CSV/JSONL
reader plus a deterministic, seedable iterator that shards *batches* across
data-parallel hosts — in the GSPMD model every host feeds its addressable slice
of the same global batch, rather than Ray pushing dataset shards to actors.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from datatunerx_tpu.data.preprocess import (
    pack_to_block,
    pad_to_block,
    preprocess_records,
)
from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.training.loss import IGNORE_INDEX


class CsvDataset:
    """Loads instruction/response records from .csv or .jsonl files.

    `columns` maps source column names → canonical names (`instruction`,
    `response`, optional `query`/`history`/`system`) — the Dataset CR feature
    mapping contract (SURVEY.md §2.3 Dataset).
    """

    def __init__(self, path: str, columns: Optional[Dict[str, str]] = None):
        self.path = path
        self.columns = columns
        self.records = self._load(path)

    @staticmethod
    def _load(path: str) -> List[Dict[str, Any]]:
        """Local paths or object-store URIs (gs://, s3://, memory://…) — the
        Dataset CR file contract is S3 URIs in the reference
        (finetune_controller.go:466-470); here any fsspec scheme works."""
        from datatunerx_tpu.utils import storage

        if not storage.exists(path):
            raise FileNotFoundError(path)
        records: List[Dict[str, Any]] = []
        if path.endswith(".jsonl") or path.endswith(".json"):
            text = storage.read_text(path).strip()
            if text.startswith("["):
                records = json.loads(text)
            else:
                records = [json.loads(line) for line in text.splitlines() if line.strip()]
        else:
            with storage.open_uri(path, "r") as f:
                records = list(csv.DictReader(f))
        return records

    def __len__(self) -> int:
        return len(self.records)

    def encode(
        self,
        template: Template | str,
        tokenizer,
        cutoff_len: int = 1024,
    ) -> List[Dict[str, List[int]]]:
        if isinstance(template, str):
            template = get_template(template, tokenizer)
        return preprocess_records(
            self.records, template, tokenizer, cutoff_len=cutoff_len,
            columns=self.columns,
        )


class BatchIterator:
    """Deterministic shuffled epochs over encoded examples → fixed-shape batches.

    - `global_batch` examples per step, padded (or packed) to `block_size`.
    - `grad_accum` reshapes to [A, mb, T].
    - `host_id`/`num_hosts` slice the global batch for multi-host feeding
      (every host computes the same permutation from the seed).
    - Drops the trailing partial batch (static shapes; the reference's dynamic
      collator has no such constraint but TPU recompilation would cost more
      than the dropped tail).
    """

    def __init__(
        self,
        examples: Sequence[Dict[str, List[int]]],
        *,
        global_batch: int,
        block_size: int,
        pad_id: int = 0,
        grad_accum: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        pack: bool = False,
        host_id: int = 0,
        num_hosts: int = 1,
        drop_remainder: bool = True,
    ):
        self.drop_remainder = drop_remainder
        if global_batch % max(grad_accum, 1) != 0:
            raise ValueError("global_batch must be divisible by grad_accum")
        if (global_batch // max(grad_accum, 1)) % num_hosts != 0:
            raise ValueError("per-step batch must be divisible by num_hosts")
        self.examples = list(examples)
        self.global_batch = global_batch
        self.block_size = block_size
        self.pad_id = pad_id
        self.grad_accum = max(grad_accum, 1)
        self.shuffle = shuffle
        self.seed = seed
        self.pack = pack
        self.host_id = host_id
        self.num_hosts = num_hosts
        if pack:
            # Pack the whole dataset once; epochs then shuffle packed rows so
            # every step keeps a static [global_batch, block_size] shape.
            packed = pack_to_block(self.examples, block_size, pad_id)
            self._rows = packed
            self._n_rows = packed["input_ids"].shape[0]
        else:
            self._rows = None
            self._n_rows = len(self.examples)

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self._n_rows // self.global_batch
        return -(-self._n_rows // self.global_batch)

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(self._n_rows)
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(order)
        for s in range(self.steps_per_epoch()):
            idx = order[s * self.global_batch : (s + 1) * self.global_batch]
            if self.pack:
                batch = {k: v[idx] for k, v in self._rows.items()}
                if len(idx) < self.global_batch:
                    batch = _pad_rows(batch, self.global_batch)
            else:
                exs = [self.examples[i] for i in idx]
                # pad the final partial batch with empty rows (labels all
                # IGNORE -> zero loss/token contribution, shapes stay static)
                exs += [{"input_ids": [], "labels": []}] * (self.global_batch - len(exs))
                batch = pad_to_block(exs, self.block_size, self.pad_id)
            batch = self._host_slice(batch)
            if self.grad_accum > 1:
                batch = {
                    k: v.reshape(self.grad_accum, -1, *v.shape[1:])
                    for k, v in batch.items()
                }
            yield batch

    def _host_slice(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if self.num_hosts == 1:
            return batch
        B = next(iter(batch.values())).shape[0]
        per = B // self.num_hosts
        lo, hi = self.host_id * per, (self.host_id + 1) * per
        return {k: v[lo:hi] for k, v in batch.items()}

    def __iter__(self):
        return self.epoch(0)


class PreferenceBatchIterator:
    """Preference-pair batches for DPO: the BatchIterator contract
    (deterministic shuffles, host slicing, grad-accum reshape, static shapes)
    applied to chosen/rejected pairs. Both sides ride two internal
    BatchIterators with the SAME seed over equal-length lists, so their
    per-epoch permutations are identical and pairs stay aligned."""

    def __init__(self, examples: Sequence[Dict[str, List[int]]], **kw):
        kw.pop("pack", None)  # packing crosses pair boundaries: not for DPO
        chosen = [{"input_ids": e["chosen_ids"], "labels": e["chosen_labels"]}
                  for e in examples]
        rejected = [{"input_ids": e["rejected_ids"],
                     "labels": e["rejected_labels"]} for e in examples]
        self._c = BatchIterator(chosen, **kw)
        self._r = BatchIterator(rejected, **kw)

    def steps_per_epoch(self) -> int:
        return self._c.steps_per_epoch()

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        for bc, br in zip(self._c.epoch(epoch), self._r.epoch(epoch)):
            yield {
                "chosen_ids": bc["input_ids"],
                "chosen_labels": bc["labels"],
                "rejected_ids": br["input_ids"],
                "rejected_labels": br["labels"],
            }

    def __iter__(self):
        return self.epoch(0)


def _pad_rows(batch: Dict[str, np.ndarray], target_rows: int) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in batch.items():
        pad_val = IGNORE_INDEX if k == "labels" else 0
        extra = np.full((target_rows - v.shape[0],) + v.shape[1:], pad_val, v.dtype)
        out[k] = np.concatenate([v, extra], axis=0)
    return out
