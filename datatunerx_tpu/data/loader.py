"""Dataset ingest + deterministic batch iteration.

Replaces Ray Data CSV ingest (reference cmd/tuning/train.py:329-351: read_csv +
rename_columns + streaming split across workers). TPU-native: a plain CSV/JSONL
reader plus a deterministic, seedable iterator that shards *batches* across
data-parallel hosts — in the GSPMD model every host feeds its addressable slice
of the same global batch, rather than Ray pushing dataset shards to actors.
"""

from __future__ import annotations

import copy
import csv
import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from datatunerx_tpu.data.preprocess import (
    pack_to_block,
    pad_to_block,
    preprocess_records,
)
from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.training.loss import IGNORE_INDEX


class CsvDataset:
    """Loads instruction/response records from .csv or .jsonl files.

    `columns` maps source column names → canonical names (`instruction`,
    `response`, optional `query`/`history`/`system`) — the Dataset CR feature
    mapping contract (SURVEY.md §2.3 Dataset).
    """

    def __init__(self, path: str, columns: Optional[Dict[str, str]] = None):
        self.path = path
        self.columns = columns
        self.records = self._load(path)

    @staticmethod
    def _load(path: str) -> List[Dict[str, Any]]:
        """Local paths or object-store URIs (gs://, s3://, memory://…) — the
        Dataset CR file contract is S3 URIs in the reference
        (finetune_controller.go:466-470); here any fsspec scheme works."""
        from datatunerx_tpu.utils import storage

        if not storage.exists(path):
            raise FileNotFoundError(path)
        records: List[Dict[str, Any]] = []
        if path.endswith(".jsonl") or path.endswith(".json"):
            text = storage.read_text(path).strip()
            if text.startswith("["):
                records = json.loads(text)
            else:
                records = [json.loads(line) for line in text.splitlines() if line.strip()]
        else:
            with storage.open_uri(path, "r") as f:
                records = list(csv.DictReader(f))
        return records

    def __len__(self) -> int:
        return len(self.records)

    def encode(
        self,
        template: Template | str,
        tokenizer,
        cutoff_len: int = 1024,
    ) -> List[Dict[str, List[int]]]:
        if isinstance(template, str):
            template = get_template(template, tokenizer)
        return preprocess_records(
            self.records, template, tokenizer, cutoff_len=cutoff_len,
            columns=self.columns,
        )


class StreamingCsvDataset:
    """Record stream over .csv/.jsonl without materializing the file
    (ROADMAP §4 streaming ingest): large datasets are read line-by-line from
    local paths or object-store URIs. JSON *arrays* can't stream — they fall
    back to a full parse."""

    def __init__(self, path: str, columns: Optional[Dict[str, str]] = None):
        from datatunerx_tpu.utils import storage

        if not storage.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.columns = columns

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        from datatunerx_tpu.utils import storage

        if self.path.endswith((".jsonl", ".json")):
            with storage.open_uri(self.path, "r") as f:
                # skip leading blank lines before sniffing for a JSON array so
                # streaming matches CsvDataset (which strips the whole text)
                first = f.readline()
                while first and not first.strip():
                    first = f.readline()
                if first.lstrip().startswith("["):  # JSON array: no streaming
                    rest = first + f.read()
                    yield from json.loads(rest)
                    return
                line = first
                while line:
                    s = line.strip()
                    if s:
                        yield json.loads(s)
                    line = f.readline()
        else:
            with storage.open_uri(self.path, "r") as f:
                yield from csv.DictReader(f)


class StreamingBatchIterator:
    """Shuffle-buffered streaming batches (tf.data ``shuffle(buffer)``
    semantics): records are encoded on the fly, held in a bounded reservoir,
    and emitted as fixed-shape [global_batch, block] batches — the dataset
    never lives in memory whole. Deterministic per (seed, epoch); host
    slicing matches BatchIterator. SFT/PT only (preference/prompt stages use
    small curated sets where whole-file load is the right call)."""

    def __init__(
        self,
        dataset: StreamingCsvDataset,
        template: Template,
        tokenizer,
        *,
        global_batch: int,
        block_size: int,
        cutoff_len: Optional[int] = None,
        pad_id: int = 0,
        grad_accum: int = 1,
        buffer_size: int = 2048,
        seed: int = 0,
        host_id: int = 0,
        num_hosts: int = 1,
        stage: str = "sft",  # sft = templated instruction pairs; pt = plain LM
        read_ahead: Optional[int] = None,  # raw-record fetch depth; 0 = sync
    ):
        if global_batch % max(grad_accum, 1) != 0:
            raise ValueError("global_batch must be divisible by grad_accum")
        if (global_batch // max(grad_accum, 1)) % num_hosts != 0:
            raise ValueError("per-step batch must be divisible by num_hosts")
        self.dataset = dataset
        self.template = template
        self.tokenizer = tokenizer
        self.global_batch = global_batch
        self.block_size = block_size
        self.cutoff_len = cutoff_len or block_size
        self.pad_id = pad_id
        self.grad_accum = max(grad_accum, 1)
        self.buffer_size = max(buffer_size, global_batch)
        self.seed = seed
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.stage = stage
        # raw-record read-ahead (data/prefetch.ReadAheadIterator): fetch and
        # encode are decoupled so a jittery remote read (gs:// line stream)
        # overlaps encoding instead of stalling the HostPrefetcher. Depth 0
        # = fully synchronous (the pre-read-ahead path, byte-identical
        # batches either way — the reader preserves record order).
        if read_ahead is None:
            read_ahead = int(os.environ.get("DTX_STREAM_READAHEAD", "64"))
        self.read_ahead = max(0, int(read_ahead))
        # per-thread tokenizer clones (see ensure_thread_safe_encoding)
        self._tls = threading.local()
        self._clone_encoders = False

    def steps_per_epoch(self) -> int:
        return -1  # unknown without a full pass; callers must use max_steps

    def ensure_thread_safe_encoding(self) -> bool:
        """Opt into per-thread tokenizer clones so this iterator can encode
        inside a HostPrefetcher worker while another thread (in-training
        generative eval) encodes with the original tokenizer.

        HF fast tokenizers wrap one Rust object whose internal state two
        threads must not borrow concurrently ("Already borrowed"
        RuntimeError); a clone per encoding thread removes the sharing
        entirely. Returns False — and leaves encoding untouched — when the
        tokenizer cannot be cloned, in which case the caller must keep the
        pipeline synchronous (tuning/train.py prints and falls back)."""
        if self._clone_encoders:
            return True
        try:
            copy.deepcopy(self.tokenizer)
        except Exception:  # noqa: BLE001 — non-clonable → caller stays sync
            return False
        self._clone_encoders = True
        return True

    def _thread_tokenizer(self):
        if not self._clone_encoders:
            return self.tokenizer
        tok = getattr(self._tls, "tokenizer", None)
        if tok is None:
            tok = copy.deepcopy(self.tokenizer)
            self._tls.tokenizer = tok
        return tok

    def _encoded(self) -> Iterator[Dict[str, List[int]]]:
        from datatunerx_tpu.data.preprocess import preprocess_pretrain_records

        tokenizer = self._thread_tokenizer()  # one epoch runs on one thread
        source: Iterator = iter(self.dataset)
        reader = None
        if self.read_ahead > 0:
            from datatunerx_tpu.data.prefetch import ReadAheadIterator

            # raw fetch on its own thread; ENCODING stays on this thread
            # (tokenizer thread-discipline unchanged — see
            # ensure_thread_safe_encoding)
            reader = ReadAheadIterator(self.dataset, depth=self.read_ahead)
            source = reader
        try:
            for rec in source:
                if self.stage == "pt":
                    out = preprocess_pretrain_records(
                        [rec], tokenizer,
                        cutoff_len=self.cutoff_len,
                        columns=self.dataset.columns,
                    )
                else:
                    out = preprocess_records(
                        [rec], self.template, tokenizer,
                        cutoff_len=self.cutoff_len,
                        columns=self.dataset.columns,
                    )
                if out:
                    yield out[0]
        finally:
            # early epoch exit (max_steps) must stop the reader thread —
            # it would otherwise block forever on the bounded queue
            if reader is not None:
                reader.close()

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.default_rng(self.seed + epoch)
        buf: List[Dict[str, List[int]]] = []
        pending: List[Dict[str, List[int]]] = []

        def emit(exs):
            batch = pad_to_block(exs, self.block_size, self.pad_id)
            if self.num_hosts > 1:
                B = batch["input_ids"].shape[0]
                per = B // self.num_hosts
                lo = self.host_id * per
                batch = {k: v[lo : lo + per] for k, v in batch.items()}
            if self.grad_accum > 1:
                batch = {
                    k: v.reshape(self.grad_accum, -1, *v.shape[1:])
                    for k, v in batch.items()
                }
            return batch

        for ex in self._encoded():
            buf.append(ex)
            if len(buf) < self.buffer_size:
                continue
            pending.append(buf.pop(int(rng.integers(len(buf)))))
            if len(pending) == self.global_batch:
                yield emit(pending)
                pending = []
        # drain: keep sampling the reservoir down to full batches only
        # (trailing partial batch dropped, as in BatchIterator)
        rng.shuffle(buf)  # type: ignore[arg-type]
        tail = pending + buf
        for s in range(len(tail) // self.global_batch):
            yield emit(tail[s * self.global_batch : (s + 1) * self.global_batch])

    def __iter__(self):
        return self.epoch(0)


class BatchIterator:
    """Deterministic shuffled epochs over encoded examples → fixed-shape batches.

    - `global_batch` examples per step, padded (or packed) to `block_size`.
    - `grad_accum` reshapes to [A, mb, T].
    - `host_id`/`num_hosts` slice the global batch for multi-host feeding
      (every host computes the same permutation from the seed).
    - Drops the trailing partial batch (static shapes; the reference's dynamic
      collator has no such constraint but TPU recompilation would cost more
      than the dropped tail).
    """

    def __init__(
        self,
        examples: Sequence[Dict[str, List[int]]],
        *,
        global_batch: int,
        block_size: int,
        pad_id: int = 0,
        grad_accum: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        pack: bool = False,
        host_id: int = 0,
        num_hosts: int = 1,
        drop_remainder: bool = True,
    ):
        self.drop_remainder = drop_remainder
        if global_batch % max(grad_accum, 1) != 0:
            raise ValueError("global_batch must be divisible by grad_accum")
        if (global_batch // max(grad_accum, 1)) % num_hosts != 0:
            raise ValueError("per-step batch must be divisible by num_hosts")
        self.examples = list(examples)
        self.global_batch = global_batch
        self.block_size = block_size
        self.pad_id = pad_id
        self.grad_accum = max(grad_accum, 1)
        self.shuffle = shuffle
        self.seed = seed
        self.pack = pack
        self.host_id = host_id
        self.num_hosts = num_hosts
        if pack:
            # Pack the whole dataset once; epochs then shuffle packed rows so
            # every step keeps a static [global_batch, block_size] shape.
            packed = pack_to_block(self.examples, block_size, pad_id)
            self._rows = packed
            self._n_rows = packed["input_ids"].shape[0]
        else:
            self._rows = None
            self._n_rows = len(self.examples)

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self._n_rows // self.global_batch
        return -(-self._n_rows // self.global_batch)

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(self._n_rows)
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(order)
        for s in range(self.steps_per_epoch()):
            idx = order[s * self.global_batch : (s + 1) * self.global_batch]
            if self.pack:
                batch = {k: v[idx] for k, v in self._rows.items()}
                if len(idx) < self.global_batch:
                    batch = _pad_rows(batch, self.global_batch)
            else:
                exs = [self.examples[i] for i in idx]
                # pad the final partial batch with empty rows (labels all
                # IGNORE -> zero loss/token contribution, shapes stay static)
                exs += [{"input_ids": [], "labels": []}] * (self.global_batch - len(exs))
                batch = pad_to_block(exs, self.block_size, self.pad_id)
            batch = self._host_slice(batch)
            if self.grad_accum > 1:
                batch = {
                    k: v.reshape(self.grad_accum, -1, *v.shape[1:])
                    for k, v in batch.items()
                }
            yield batch

    def _host_slice(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if self.num_hosts == 1:
            return batch
        B = next(iter(batch.values())).shape[0]
        per = B // self.num_hosts
        lo, hi = self.host_id * per, (self.host_id + 1) * per
        return {k: v[lo:hi] for k, v in batch.items()}

    def __iter__(self):
        return self.epoch(0)


class PreferenceBatchIterator:
    """Preference-pair batches for DPO: the BatchIterator contract
    (deterministic shuffles, host slicing, grad-accum reshape, static shapes)
    applied to chosen/rejected pairs. Both sides ride two internal
    BatchIterators with the SAME seed over equal-length lists, so their
    per-epoch permutations are identical and pairs stay aligned."""

    def __init__(self, examples: Sequence[Dict[str, List[int]]], **kw):
        kw.pop("pack", None)  # packing crosses pair boundaries: not for DPO
        chosen = [{"input_ids": e["chosen_ids"], "labels": e["chosen_labels"]}
                  for e in examples]
        rejected = [{"input_ids": e["rejected_ids"],
                     "labels": e["rejected_labels"]} for e in examples]
        self._c = BatchIterator(chosen, **kw)
        self._r = BatchIterator(rejected, **kw)

    def steps_per_epoch(self) -> int:
        return self._c.steps_per_epoch()

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        for bc, br in zip(self._c.epoch(epoch), self._r.epoch(epoch)):
            yield {
                "chosen_ids": bc["input_ids"],
                "chosen_labels": bc["labels"],
                "rejected_ids": br["input_ids"],
                "rejected_labels": br["labels"],
            }

    def __iter__(self):
        return self.epoch(0)


class PromptBatchIterator:
    """Prompt-only batches for PPO rollouts (training/ppo.py): LEFT-padded
    ``prompt_ids`` [B, block] + ``prompt_mask``, matching the generation
    convention (pads in front, real tokens at the end so the last column is
    the last prompt token). Same contract as BatchIterator: deterministic
    shuffles, host slicing, static shapes, trailing partial batch dropped."""

    def __init__(
        self,
        examples: Sequence[Dict[str, List[int]]],
        *,
        global_batch: int,
        block_size: int,
        pad_id: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        host_id: int = 0,
        num_hosts: int = 1,
        **_ignored,  # grad_accum/pack accepted for contract, meaningless here
    ):
        if global_batch % num_hosts != 0:
            raise ValueError("global_batch must be divisible by num_hosts")
        self.examples = [e for e in examples if e.get("prompt_ids")]
        self.global_batch = global_batch
        self.block_size = block_size
        self.pad_id = pad_id
        self.shuffle = shuffle
        self.seed = seed
        self.host_id = host_id
        self.num_hosts = num_hosts

    def steps_per_epoch(self) -> int:
        return len(self.examples) // self.global_batch

    def epoch(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(len(self.examples))
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(order)
        T = self.block_size
        for s in range(self.steps_per_epoch()):
            idx = order[s * self.global_batch : (s + 1) * self.global_batch]
            ids = np.full((len(idx), T), self.pad_id, np.int32)
            mask = np.zeros((len(idx), T), np.int32)
            for r, i in enumerate(idx):
                p = self.examples[i]["prompt_ids"][-T:]
                ids[r, T - len(p):] = p
                mask[r, T - len(p):] = 1
            batch = {"prompt_ids": ids, "prompt_mask": mask}
            if self.num_hosts > 1:
                per = self.global_batch // self.num_hosts
                lo = self.host_id * per
                batch = {k: v[lo : lo + per] for k, v in batch.items()}
            yield batch

    def __iter__(self):
        return self.epoch(0)


def _pad_rows(batch: Dict[str, np.ndarray], target_rows: int) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in batch.items():
        pad_val = IGNORE_INDEX if k == "labels" else 0
        extra = np.full((target_rows - v.shape[0],) + v.shape[1:], pad_val, v.dtype)
        out[k] = np.concatenate([v, extra], axis=0)
    return out
