from datatunerx_tpu.data.templates import Template, get_template, list_templates
from datatunerx_tpu.data.preprocess import (
    encode_supervised_example,
    pad_to_block,
    preprocess_records,
)
from datatunerx_tpu.data.loader import CsvDataset, BatchIterator
from datatunerx_tpu.data.prefetch import (
    DevicePrefetcher,
    HostPrefetcher,
    MetricsBuffer,
    PipelineStats,
    PlacedBatch,
    prefetch_batches,
)

__all__ = [
    "DevicePrefetcher",
    "HostPrefetcher",
    "MetricsBuffer",
    "PipelineStats",
    "PlacedBatch",
    "prefetch_batches",
    "Template",
    "get_template",
    "list_templates",
    "encode_supervised_example",
    "pad_to_block",
    "preprocess_records",
    "CsvDataset",
    "BatchIterator",
]
