"""Supervised SFT preprocessing: encode + prompt-mask + fixed-shape padding.

Behavior-parity with the reference preprocessor (reference
cmd/tuning/train.py:58-135):

- column-mapped records with `instruction`/`response` (+ optional `query`
  appended to instruction with a newline, `history`, `system`);
- skip records where either field is empty/non-string;
- per-turn proportional truncation to cutoff_len, prompt masked to
  IGNORE_INDEX; efficient_eos turns carry eos as first label token of the
  *source* span; final eos appended for efficient_eos templates;
- final truncation to cutoff_len.

TPU-first deltas: batches are padded to a static block_size (XLA needs static
shapes; the reference pads dynamically per batch, train.py:282-286), and an
optional greedy packer concatenates short examples with segment_ids — our
attention masks cross-segment pairs, which dynamic-padding stacks can't do.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from datatunerx_tpu.data.templates import Template
from datatunerx_tpu.training.loss import IGNORE_INDEX


def map_columns(record: Dict[str, Any], columns: Optional[Dict[str, str]]) -> Dict[str, Any]:
    """Rename record keys per the Dataset CR feature map (reference
    cmd/tuning/train.py:54-56; mapping built by the controller from
    DatasetInfo.Features[].{Name,MapTo},
    internal/controller/finetune/finetune_controller.go:655-680)."""
    if not columns:
        return record
    return {columns.get(k, k): v for k, v in record.items()}


def encode_supervised_example(
    template: Template,
    tokenizer,
    query: str,
    response: str,
    history: Optional[List[Tuple[str, str]]] = None,
    system: Optional[str] = None,
    cutoff_len: int = 1024,
) -> Tuple[List[int], List[int]]:
    """Returns (input_ids, labels); None-equivalent empties are the caller's
    filter responsibility."""
    input_ids: List[int] = []
    labels: List[int] = []
    for turn_idx, (source_ids, target_ids) in enumerate(
        template.encode_turns(tokenizer, query, response, history, system)
    ):
        total = len(source_ids) + len(target_ids)
        max_src = int(cutoff_len * (len(source_ids) / total)) if total else 0
        max_tgt = int(cutoff_len * (len(target_ids) / total)) if total else 0
        if len(source_ids) > max_src:
            source_ids = source_ids[:max_src]
        if len(target_ids) > max_tgt:
            target_ids = target_ids[:max_tgt]

        if turn_idx != 0 and template.efficient_eos:
            source_mask = [tokenizer.eos_token_id] + [IGNORE_INDEX] * (len(source_ids) - 1)
        else:
            source_mask = [IGNORE_INDEX] * len(source_ids)

        input_ids += source_ids + target_ids
        labels += source_mask + target_ids

    if template.efficient_eos:
        input_ids += [tokenizer.eos_token_id]
        labels += [tokenizer.eos_token_id]

    return input_ids[:cutoff_len], labels[:cutoff_len]


def preprocess_records(
    records: Iterable[Dict[str, Any]],
    template: Template,
    tokenizer,
    cutoff_len: int = 1024,
    columns: Optional[Dict[str, str]] = None,
) -> List[Dict[str, List[int]]]:
    out = []
    for rec in records:
        rec = map_columns(rec, columns)
        query, response = rec.get("instruction"), rec.get("response")
        if not (isinstance(query, str) and isinstance(response, str)
                and query != "" and response != ""):
            continue
        if rec.get("query"):
            query = query + "\n" + rec["query"]
        ids, labels = encode_supervised_example(
            template, tokenizer, query, response,
            history=rec.get("history"), system=rec.get("system"),
            cutoff_len=cutoff_len,
        )
        out.append({"input_ids": ids, "labels": labels,
                    "attention_mask": [1] * len(ids)})
    return out


def preprocess_pretrain_records(
    records: Iterable[Dict[str, Any]],
    tokenizer,
    cutoff_len: int = 1024,
    columns: Optional[Dict[str, str]] = None,
) -> List[Dict[str, List[int]]]:
    """Plain-LM pretraining (``--stage pt``; the reference lists pt in its
    stage enum, cmd/tuning/parser.py:117-120, but its runtime only ever
    builds the SFT trainer): records carry a ``text`` column (the Dataset CR
    column map applies — map your corpus column to ``text``), falling back to
    ``instruction``+``response`` concatenation so SFT-shaped files still
    work. Every token is a label: no template, no prompt masking. Pairs well
    with ``--pack_sequences``."""
    bos = getattr(tokenizer, "bos_token_id", None)
    add_bos = bool(getattr(tokenizer, "add_bos_token", False)) and bos is not None
    eos = tokenizer.eos_token_id
    out = []
    for rec in records:
        rec = map_columns(rec, columns)
        text = rec.get("text")
        if not isinstance(text, str) or not text:
            parts = [rec.get("instruction"), rec.get("response")]
            text = "\n".join(p for p in parts if isinstance(p, str) and p)
        if not text:
            continue
        ids = tokenizer.encode(text, add_special_tokens=False)
        if add_bos:
            ids = [bos] + ids
        if eos is not None:
            ids = ids + [eos]
        ids = ids[:cutoff_len]
        out.append({"input_ids": ids, "labels": list(ids),
                    "attention_mask": [1] * len(ids)})
    return out


def preprocess_preference_records(
    records: Iterable[Dict[str, Any]],
    template: Template,
    tokenizer,
    cutoff_len: int = 1024,
    columns: Optional[Dict[str, str]] = None,
) -> List[Dict[str, List[int]]]:
    """DPO preference pairs: records carry ``instruction`` + ``chosen`` +
    ``rejected`` (canonical names; the Dataset CR column map applies as for
    SFT). Each side is encoded exactly like an SFT example — prompt masked,
    response labeled — so sequence log-probs cover response tokens only.

    The reference reserves ``--stage dpo`` in its schema
    (cmd/tuning/parser.py:117-120, dpo knobs :170-185) but ships no runtime
    for it; this is new capability."""
    out = []
    for rec in records:
        rec = map_columns(rec, columns)
        query = rec.get("instruction")
        chosen, rejected = rec.get("chosen"), rec.get("rejected")
        if not all(isinstance(v, str) and v != ""
                   for v in (query, chosen, rejected)):
            continue
        if rec.get("query"):
            query = query + "\n" + rec["query"]
        pair = {}
        for side, response in (("chosen", chosen), ("rejected", rejected)):
            ids, labels = encode_supervised_example(
                template, tokenizer, query, response,
                history=rec.get("history"), system=rec.get("system"),
                cutoff_len=cutoff_len,
            )
            pair[f"{side}_ids"] = ids
            pair[f"{side}_labels"] = labels
        out.append(pair)
    return out


def preprocess_prompt_records(
    records: Iterable[Dict[str, Any]],
    template: Template,
    tokenizer,
    cutoff_len: int = 1024,
    columns: Optional[Dict[str, str]] = None,
) -> List[Dict[str, List[int]]]:
    """PPO prompt sets: only ``instruction`` (+ optional query/history/system)
    is consumed — the policy GENERATES the response, so any ``response``
    column is ignored. Encoding matches the generative-eval prompt encoding
    (training/generate.py) so PPO rollouts see the same template framing the
    served model will."""
    out = []
    for rec in records:
        rec = map_columns(rec, columns)
        query = rec.get("instruction")
        if not (isinstance(query, str) and query):
            continue
        if rec.get("query"):
            query = query + "\n" + rec["query"]
        prompt_ids, _ = template.encode_oneturn(
            tokenizer, query, "", rec.get("history"), rec.get("system"))
        if not prompt_ids:
            continue
        out.append({"prompt_ids": prompt_ids[-cutoff_len:]})
    return out


def pad_to_block(
    examples: Sequence[Dict[str, List[int]]],
    block_size: int,
    pad_id: int = 0,
    use_native: bool = True,
) -> Dict[str, np.ndarray]:
    """Right-pad each example to the static block_size. The hot loop runs in
    the C++ extension when available (datatunerx_tpu/native)."""
    if use_native and examples:
        from datatunerx_tpu import native

        out = native.fill_batch_native(examples, block_size, pad_id, IGNORE_INDEX)
        if out is not None:
            return out
    B = len(examples)
    input_ids = np.full((B, block_size), pad_id, np.int32)
    labels = np.full((B, block_size), IGNORE_INDEX, np.int32)
    attn = np.zeros((B, block_size), np.int32)
    for i, ex in enumerate(examples):
        n = min(len(ex["input_ids"]), block_size)
        input_ids[i, :n] = ex["input_ids"][:n]
        labels[i, :n] = ex["labels"][:n]
        attn[i, :n] = 1
    return {"input_ids": input_ids, "labels": labels, "attention_mask": attn}


def pack_to_block(
    examples: Sequence[Dict[str, List[int]]],
    block_size: int,
    pad_id: int = 0,
    use_native: bool = True,
) -> Dict[str, np.ndarray]:
    """Greedy first-fit packing of short examples into block_size rows with
    segment_ids; cross-segment attention is masked by the model. Raises the
    useful-token density vs plain padding (TPU static shapes make padding
    waste real FLOPs)."""
    if use_native and examples:
        from datatunerx_tpu import native

        out = native.pack_batch_native(examples, block_size, pad_id, IGNORE_INDEX)
        if out is not None:
            return out
    rows: List[List[Dict[str, List[int]]]] = []
    used: List[int] = []
    for ex in sorted(examples, key=lambda e: -len(e["input_ids"])):
        n = min(len(ex["input_ids"]), block_size)
        for i, u in enumerate(used):
            if u + n <= block_size:
                rows[i].append(ex)
                used[i] += n
                break
        else:
            rows.append([ex])
            used.append(n)

    B = len(rows)
    input_ids = np.full((B, block_size), pad_id, np.int32)
    labels = np.full((B, block_size), IGNORE_INDEX, np.int32)
    attn = np.zeros((B, block_size), np.int32)
    segs = np.zeros((B, block_size), np.int32)
    positions = np.zeros((B, block_size), np.int32)
    for i, row in enumerate(rows):
        off = 0
        for j, ex in enumerate(row, start=1):
            n = min(len(ex["input_ids"]), block_size - off)
            input_ids[i, off : off + n] = ex["input_ids"][:n]
            labels[i, off : off + n] = ex["labels"][:n]
            # the shifted CE loss reads labels[t+1] from position t; the first
            # token of a segment must never be trained from the previous
            # segment's last token
            labels[i, off] = IGNORE_INDEX
            attn[i, off : off + n] = 1
            segs[i, off : off + n] = j
            positions[i, off : off + n] = np.arange(n)
            off += n
    return {
        "input_ids": input_ids,
        "labels": labels,
        "attention_mask": attn,
        "segment_ids": segs,
        "positions": positions,
    }
