"""Prompt-template registry: encodes (query, response, history, system) into
per-turn (prompt_ids, response_ids) pairs.

Behavior-parity port of the reference registry semantics (reference
cmd/tuning/template.py:24-120 for the encode algorithm, :228-620 for the 18
registered templates; golden-token tests in tests/test_templates.py pin us to
the reference algorithm's output). Key semantics:

- A template is prefix/prompt/system/sep token-or-text sequences. ``{{system}}``,
  ``{{query}}``, ``{{idx}}`` substitute once per element. Dict elements are
  literal special tokens resolved via ``convert_tokens_to_ids``.
- Standard encoding: turn 0 = [bos + prefix + sep + query | resp + eos],
  turn t = [sep + bos + query | resp + eos]. If prefix renders empty, turn 0 is
  just [bos + query].
- llama2-family templates fold "<<SYS>>…" into the first query and emit
  [bos + "[INST] … [/INST] " | resp + eos] per turn with no sep.
- ``efficient_eos`` (baichuan/qwen/chatglm/…): no eos after each response; a
  single eos is appended at sequence end by the supervised preprocessor, and
  later turns carry eos as the first *label* token (see preprocess.py).
- Tokenizer fixing: missing eos → "<|endoftext|>"; missing pad → eos; template
  stop words are registered as additional special tokens.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

Piece = Union[str, Dict[str, str]]  # text or {"token": "<special>"}


@dataclasses.dataclass(frozen=True)
class Template:
    name: str
    prefix: Tuple[Piece, ...]
    prompt: Tuple[Piece, ...]
    system: str
    sep: Tuple[Piece, ...]
    stop_words: Tuple[str, ...] = ()
    use_history: bool = True
    efficient_eos: bool = False

    # llama2-style templates get special turn encoding (detected on name, like
    # the reference's register_template does).
    @property
    def is_llama2_style(self) -> bool:
        return "llama2" in self.name

    # ------------------------------------------------------------- rendering
    def _render(
        self,
        tokenizer,
        pieces: Sequence[Piece],
        *,
        system: Optional[str] = None,
        query: Optional[str] = None,
        idx: Optional[str] = None,
    ) -> List[int]:
        ids: List[int] = []
        for piece in pieces:
            if isinstance(piece, dict):
                ids.append(tokenizer.convert_tokens_to_ids(piece["token"]))
                continue
            text = piece
            if system is not None:
                text = text.replace("{{system}}", system, 1)
            if query is not None:
                text = text.replace("{{query}}", query, 1)
            if idx is not None:
                text = text.replace("{{idx}}", idx, 1)
            if text:
                ids.extend(tokenizer.encode(text, add_special_tokens=False))
        return ids

    def _special_ids(self, tokenizer) -> Tuple[List[int], List[int]]:
        bos = (
            [tokenizer.bos_token_id]
            if tokenizer.bos_token_id is not None
            and getattr(tokenizer, "add_bos_token", True)
            else []
        )
        if tokenizer.eos_token_id is None:
            raise ValueError("EOS token is required.")
        eos = [] if self.efficient_eos else [tokenizer.eos_token_id]
        return bos, eos

    # -------------------------------------------------------------- encoding
    def encode_turns(
        self,
        tokenizer,
        query: str,
        response: str,
        history: Optional[List[Tuple[str, str]]] = None,
        system: Optional[str] = None,
    ) -> List[Tuple[List[int], List[int]]]:
        """All (prompt_ids, response_ids) pairs, oldest turn first."""
        system = system or self.system
        turns = (list(history) if (history and self.use_history) else []) + [
            (query, response)
        ]
        bos, eos = self._special_ids(tokenizer)

        pairs: List[Tuple[List[int], List[int]]] = []
        if self.is_llama2_style:
            for i, (q, r) in enumerate(turns):
                if i == 0:
                    q = str(self.prefix[0]).replace("{{system}}", system) + q
                q_ids = self._render(tokenizer, self.prompt, query=q)
                r_ids = tokenizer.encode(r, add_special_tokens=False) if r else []
                pairs.append((bos + q_ids, r_ids + eos))
            return pairs

        sep_ids = self._render(tokenizer, self.sep)
        for i, (q, r) in enumerate(turns):
            if i == 0:
                prefix_ids = self._render(tokenizer, self.prefix, system=system)
                lead = bos + prefix_ids + sep_ids if prefix_ids else bos
            else:
                lead = sep_ids + bos
            q_ids = self._render(tokenizer, self.prompt, query=q, idx=str(i))
            r_ids = tokenizer.encode(r, add_special_tokens=False) if r else []
            pairs.append((lead + q_ids, r_ids + eos))
        return pairs

    def encode_oneturn(
        self, tokenizer, query, response, history=None, system=None
    ) -> Tuple[List[int], List[int]]:
        """(full prompt ids incl. history, final response ids)."""
        pairs = self.encode_turns(tokenizer, query, response, history, system)
        prompt: List[int] = []
        for q_ids, r_ids in pairs[:-1]:
            prompt += q_ids + r_ids
        return prompt + pairs[-1][0], pairs[-1][1]


def fix_tokenizer(tokenizer, template: Optional["Template"]) -> None:
    """Reference get_template_and_fix_tokenizer side effects
    (cmd/tuning/template.py:201-222)."""
    if tokenizer.eos_token_id is None:
        tokenizer.eos_token = "<|endoftext|>"
    if tokenizer.pad_token_id is None:
        tokenizer.pad_token = tokenizer.eos_token
    if template is not None and template.stop_words:
        tokenizer.add_special_tokens(
            dict(additional_special_tokens=list(template.stop_words)),
            replace_additional_special_tokens=False,
        )


# ======================================================================
# Registry. Spec strings/tokens mirror the reference registrations
# (cmd/tuning/template.py:228-620) — behavior parity requires identical
# format strings; see tests/goldens/templates.json.
# ======================================================================

_T = lambda token: {"token": token}  # noqa: E731

_DEFAULT_SYSTEM = (
    "A chat between a curious user and an artificial intelligence assistant. "
    "The assistant gives helpful, detailed, and polite answers to the user's questions."
)

_SPECS: Dict[str, Dict[str, Any]] = {
    # language-model inference, no history
    "vanilla": dict(prefix=[], prompt=["{{query}}"], system="", sep=[], use_history=False),
    "default": dict(
        prefix=["{{system}}"],
        prompt=["Human: {{query}}\nAssistant: "],
        system=_DEFAULT_SYSTEM,
        sep=["\n"],
    ),
    "llama2": dict(
        prefix=["<<SYS>>\n{{system}}\n<</SYS>>\n\n"],
        prompt=["[INST] {{query}} [/INST] "],
        system=(
            "You are a helpful, respectful and honest assistant. "
            "Always answer as helpfully as possible, while being safe.  "
            "Your answers should not include any harmful, unethical, "
            "racist, sexist, toxic, dangerous, or illegal content. "
            "Please ensure that your responses are socially unbiased and positive in nature.\n\n"
            "If a question does not make any sense, or is not factually coherent, "
            "explain why instead of answering something not correct. "
            "If you don't know the answer to a question, please don't share false information."
        ),
        sep=[],
    ),
    "llama2_zh": dict(
        prefix=["<<SYS>>\n{{system}}\n<</SYS>>\n\n"],
        prompt=["[INST] {{query}} [/INST] "],
        system="You are a helpful assistant. 你是一个乐于助人的助手。",
        sep=[],
    ),
    "alpaca": dict(
        prefix=["{{system}}"],
        prompt=["### Instruction:\n{{query}}\n\n### Response:\n"],
        system=(
            "Below is an instruction that describes a task. "
            "Write a response that appropriately completes the request."
        ),
        sep=["\n\n"],
    ),
    "vicuna": dict(
        prefix=["{{system}}"],
        prompt=["USER: {{query}} ASSISTANT:"],
        system=_DEFAULT_SYSTEM,
        sep=[],
    ),
    "belle": dict(
        prefix=["{{system}}"], prompt=["Human: {{query}}\n\nBelle: "], system="",
        sep=["\n\n"],
    ),
    "ziya": dict(
        prefix=["{{system}}"],
        prompt=[_T("<human>"), ":{{query}}\n", _T("<bot>"), ":"],
        system="",
        sep=["\n"],
    ),
    "aquila": dict(
        prefix=["{{system}}"],
        prompt=["Human: {{query}}###Assistant:"],
        system=(
            "A chat between a curious human and an artificial intelligence assistant. "
            "The assistant gives helpful, detailed, and polite answers to the human's questions."
        ),
        sep=["###"],
        stop_words=["</s>"],
        efficient_eos=True,
    ),
    "intern": dict(
        prefix=["{{system}}"],
        prompt=["<|User|>:{{query}}", _T("<eoh>"), "\n<|Bot|>:"],
        system="",
        sep=[_T("<eoa>"), "\n"],
        stop_words=["<eoa>"],
        efficient_eos=True,
    ),
    "baichuan": dict(
        prefix=["{{system}}"],
        prompt=[_T("<reserved_102>"), "{{query}}", _T("<reserved_103>")],
        system="",
        sep=[],
        efficient_eos=True,
    ),
    "baichuan2": dict(
        prefix=["{{system}}"],
        prompt=[_T("<reserved_106>"), "{{query}}", _T("<reserved_107>")],
        system="",
        sep=[],
        efficient_eos=True,
    ),
    "starchat": dict(
        prefix=[_T("<|system|>"), "\n{{system}}"],
        prompt=[_T("<|user|>"), "\n{{query}}", _T("<|end|>"), "\n", _T("<|assistant|>")],
        system="",
        sep=[_T("<|end|>"), "\n"],
        stop_words=["<|end|>"],
        efficient_eos=True,
    ),
    "chatml": dict(
        prefix=[_T("<|im_start|>"), "system\n{{system}}"],
        prompt=[
            _T("<|im_start|>"), "user\n{{query}}", _T("<|im_end|>"), "\n",
            _T("<|im_start|>"), "assistant\n",
        ],
        system="You are a helpful assistant.",
        sep=[_T("<|im_end|>"), "\n"],
        stop_words=["<|im_end|>"],
        efficient_eos=True,
    ),
    "chatglm2": dict(
        prefix=[_T("[gMASK]"), _T("sop"), "{{system}}"],
        prompt=["[Round {{idx}}]\n\n问：{{query}}\n\n答："],
        system="",
        sep=["\n\n"],
        efficient_eos=True,
    ),
    "chatglm3": dict(
        prefix=[_T("[gMASK]"), _T("sop"), "{{system}}"],
        prompt=[_T("<|user|>"), "\n", "{{query}}", _T("<|assistant|>")],
        system="",
        sep=[],
        stop_words=["<|user|>", "<|observation|>"],
        efficient_eos=True,
    ),
    "openchat": dict(
        prefix=["{{system}}"],
        prompt=["GPT4 User: {{query}}", _T("<|end_of_turn|>"), "GPT4 Assistant:"],
        system="",
        sep=[_T("<|end_of_turn|>")],
        efficient_eos=True,
    ),
    "xverse": dict(
        prefix=["{{system}}"],
        prompt=["Human: {{query}}\n\nAssistant: "],
        system="",
        sep=[],
    ),
}

TEMPLATES: Dict[str, Template] = {
    name: Template(
        name=name,
        prefix=tuple(spec["prefix"]),
        prompt=tuple(spec["prompt"]),
        system=spec["system"],
        sep=tuple(spec["sep"]),
        stop_words=tuple(spec.get("stop_words", ())),
        use_history=spec.get("use_history", True),
        efficient_eos=spec.get("efficient_eos", False),
    )
    for name, spec in _SPECS.items()
}


def get_template(name: str, tokenizer=None) -> Template:
    if name not in TEMPLATES:
        raise KeyError(f"template {name!r} does not exist; have {sorted(TEMPLATES)}")
    template = TEMPLATES[name]
    if tokenizer is not None:
        fix_tokenizer(tokenizer, template)
    return template


def list_templates() -> List[str]:
    return sorted(TEMPLATES)
