"""Declarative SLOs + multi-window burn-rate evaluation over the shared
registry.

PR 7 gave every plane histograms and counters; nothing *judged* them. This
module turns those raw series into verdicts every consumer shares — the
gateway's and serving server's ``GET /debug/slo``, the promotion guard's
optional SLO mode, and the load-replay epilogue all run the same evaluator,
so "the fleet is healthy" means one thing everywhere.

An SLO binds an SLI to an objective over evaluation windows:

  {"name": "serving-ttft-p95", "objective": 0.95,
   "windows_s": [300, 3600],
   "sli": {"kind": "latency", "metric": "dtx_serving_ttft_ms",
           "threshold_ms": 250}}

Two SLI kinds, both defined as a good/total event ratio so the burn-rate
math (Google SRE workbook ch. 5) is uniform:

  latency      — good = observations at or under the threshold, read from
                 the histogram's cumulative buckets (the threshold snaps UP
                 to the nearest bucket edge; the effective edge is reported).
                 ``objective 0.95 + threshold_ms 250`` is exactly
                 "p95 <= 250ms".
  error_ratio  — bad = counter series whose labels match the ``bad``
                 regexes (e.g. {"code": "^5"}), total = all series of the
                 metric (optionally ``match``-filtered first).

The evaluator samples cumulative (good, total) pairs into a bounded ring;
a window's compliance is the delta between now and the sample one window
ago, and its burn rate is ``(1 - compliance) / (1 - objective)`` — burn 1.0
spends the error budget exactly at the objective's rate, burn > 1.0 in
EVERY populated window is the multi-window page condition (fast window
confirms it's happening now, slow window confirms it's material).

``dtx_slo_*`` gauges (objective / compliance / burn_rate{window} / error
budget remaining / compliant) are restated into the same registry the SLIs
read from, so the SLO plane is itself scrapable.

Hot-path discipline: nothing here runs on a request path. Sampling and
evaluation walk registry snapshots at /debug/slo time, on the background
sampler tick, or at a promotion stage boundary.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from datatunerx_tpu.obs.metrics import Histogram, Metric, Registry

DEFAULT_WINDOWS_S = (300.0, 3600.0)


@dataclass(frozen=True)
class SLO:
    """One declarative objective. Build via ``SLO.from_dict`` (validates)
    or the ``default_slos``/``parse_slos`` helpers."""

    name: str
    objective: float
    sli: dict
    windows_s: Tuple[float, ...] = DEFAULT_WINDOWS_S
    description: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "SLO":
        name = str(d.get("name") or "")
        if not name or not re.match(r"^[a-zA-Z0-9_.-]+$", name):
            raise ValueError(f"SLO needs a [a-zA-Z0-9_.-]+ name, got {name!r}")
        try:
            objective = float(d["objective"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(f"SLO {name!r}: objective must be a number")
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"SLO {name!r}: objective must be in (0, 1) — 1.0 leaves "
                "no error budget to burn")
        sli = dict(d.get("sli") or {})
        kind = sli.get("kind")
        metric = sli.get("metric")
        if not metric:
            raise ValueError(f"SLO {name!r}: sli.metric is required")
        if kind == "latency":
            # threshold in the metric's native unit; threshold_ms is the
            # spelled-out alias for the *_ms histograms
            thr = sli.get("threshold", sli.get("threshold_ms"))
            if thr is None:
                raise ValueError(
                    f"SLO {name!r}: latency sli needs threshold (or "
                    "threshold_ms)")
            sli["threshold"] = float(thr)
        elif kind == "error_ratio":
            bad = sli.get("bad") or {}
            if not isinstance(bad, dict) or not bad:
                raise ValueError(
                    f"SLO {name!r}: error_ratio sli needs a bad "
                    "label-regex map, e.g. {\"code\": \"^5\"}")
            for k, v in bad.items():
                re.compile(str(v))  # fail loud on a bad regex
        else:
            raise ValueError(
                f"SLO {name!r}: sli.kind must be latency or error_ratio, "
                f"got {kind!r}")
        windows = tuple(float(w) for w in
                        (d.get("windows_s") or DEFAULT_WINDOWS_S))
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(f"SLO {name!r}: windows_s must be positive")
        return cls(name=name, objective=objective, sli=sli,
                   windows_s=tuple(sorted(windows)),
                   description=str(d.get("description") or ""))


def parse_slos(doc) -> List[SLO]:
    """A spec document (list of SLO dicts, or {"slos": [...]}) → SLOs."""
    if isinstance(doc, dict):
        doc = doc.get("slos")
    if not isinstance(doc, list) or not doc:
        raise ValueError("SLO config must be a non-empty list of SLO "
                         "objects (or {\"slos\": [...]})")
    slos = [SLO.from_dict(d) for d in doc]
    names = [s.name for s in slos]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO names in config: {sorted(names)}")
    return slos


def load_slos(path_or_json: str) -> List[SLO]:
    """Parse SLOs from a file path or an inline JSON string (starts with
    '[' or '{') — the CLI's --slo flag accepts both."""
    text = path_or_json.strip()
    if not text.startswith(("[", "{")):
        with open(path_or_json, encoding="utf-8") as f:
            text = f.read()
    return parse_slos(json.loads(text))


def default_slos(plane: str) -> List[SLO]:
    """The out-of-the-box objectives each plane judges itself against when
    no --slo_config is given. Deliberately loose — they exist so /debug/slo
    answers something useful from first boot, not to page anyone."""
    if plane == "gateway":
        return [
            SLO.from_dict({
                "name": "gateway-availability", "objective": 0.99,
                "description": "non-5xx answers / all answers (429 shed is "
                               "a served answer: the gateway protected the "
                               "fleet, it did not fail)",
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_gateway_requests_total",
                        "bad": {"code": "^5"}}}),
            SLO.from_dict({
                "name": "gateway-fast-requests", "objective": 0.95,
                "description": "p95 end-to-end gateway latency under 2.5s",
                "sli": {"kind": "latency",
                        "metric": "dtx_gateway_request_latency_seconds",
                        "threshold": 2.5}}),
        ]
    if plane == "serving":
        return [
            SLO.from_dict({
                "name": "serving-availability", "objective": 0.99,
                "description": "non-5xx answers / all answers",
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_serving_requests_total",
                        "bad": {"code": "^5"}}}),
            SLO.from_dict({
                "name": "serving-ttft-p95", "objective": 0.95,
                "description": "p95 time-to-first-token under 250ms",
                "sli": {"kind": "latency",
                        "metric": "dtx_serving_ttft_ms",
                        "threshold_ms": 250}}),
        ]
    if plane == "loadgen":
        return [
            SLO.from_dict({
                "name": "loadgen-availability", "objective": 0.99,
                "description": "replayed requests answered without a "
                               "server-side failure",
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_loadgen_requests_total",
                        "bad": {"code": "^5"}}}),
            SLO.from_dict({
                "name": "loadgen-fast-ttft", "objective": 0.90,
                "description": "p90 first-token latency under 2.5s as the "
                               "client saw it",
                "sli": {"kind": "latency",
                        "metric": "dtx_loadgen_ttft_ms",
                        "threshold_ms": 2500}}),
        ]
    raise ValueError(f"no default SLOs for plane {plane!r}")


def evaluate_window(good: float, total: float, objective: float) -> dict:
    """The one window-verdict formula everyone shares: compliance,
    burn rate, and the compliant bit. No data = vacuously compliant
    (a dead service should page via an absence alert, not divide by
    zero here)."""
    if total <= 0:
        return {"good": 0, "total": 0, "compliance": None,
                "burn_rate": None, "compliant": True, "no_data": True}
    compliance = good / total
    burn = (1.0 - compliance) / (1.0 - objective)
    return {"good": int(good), "total": int(total),
            "compliance": round(compliance, 6),
            "burn_rate": round(burn, 4),
            "compliant": compliance >= objective, "no_data": False}


@dataclass
class _Sample:
    t: float
    cumulative: Dict[str, Tuple[float, float]] = field(default_factory=dict)


class SLOEvaluator:
    """Samples cumulative (good, total) pairs off a Registry and judges
    SLOs over windows. One instance per server/run; thread-safe.

    Three consumers, three entry points:

      report()          — /debug/slo: take a sample, evaluate every spec
                          window, restate the dtx_slo_* gauges.
      verdicts(...)     — judge each SLO from the most recent sample (or
                          the earliest sample at/after ``since_t``) to NOW:
                          the promotion guard's per-stage window (sample at
                          stage begin, judge at stage end) and the replay
                          epilogue's whole-run window.
      start()/stop()    — background sampler so the spec windows have
                          history without anyone polling /debug/slo.
    """

    def __init__(self, registry: Registry, slos: Sequence[SLO],
                 history_slack: float = 1.5):
        self.registry = registry
        self.slos = list(slos)
        if not self.slos:
            raise ValueError("SLOEvaluator needs at least one SLO")
        self._max_window = max(w for s in self.slos for w in s.windows_s)
        self._history_s = self._max_window * history_slack
        self._samples: "deque[_Sample]" = deque()
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_results: List[dict] = []
        self.sample()  # the time-zero baseline every window falls back to

    # --------------------------------------------------------- SLI reading
    def _cumulative(self, slo: SLO) -> Tuple[float, float]:
        m = self.registry.get(slo.sli["metric"])
        if m is None:
            return (0.0, 0.0)
        if slo.sli["kind"] == "latency":
            if not isinstance(m, Histogram):
                return (0.0, 0.0)
            counts = m.bucket_counts()
            total = counts[-1][1] if counts else 0
            thr = slo.sli["threshold"]
            good = 0
            for edge, cum in counts:
                if edge >= thr:
                    good = cum
                    break
            return (float(good), float(total))
        # error_ratio
        if not isinstance(m, Metric):
            return (0.0, 0.0)
        series = m.series()
        match = slo.sli.get("match") or {}
        bad_re = {k: re.compile(str(v))
                  for k, v in slo.sli["bad"].items()}
        match_re = {k: re.compile(str(v)) for k, v in match.items()}
        total = bad = 0.0
        for key, value in series.items():
            labels = dict(key)
            if any(not r.search(str(labels.get(k, "")))
                   for k, r in match_re.items()):
                continue
            total += value
            if all(r.search(str(labels.get(k, "")))
                   for k, r in bad_re.items()):
                bad += value
        return (total - bad, total)

    def effective_threshold(self, slo: SLO) -> Optional[float]:
        """The bucket edge a latency threshold actually snaps to (None for
        error-ratio SLIs or an unregistered metric)."""
        if slo.sli["kind"] != "latency":
            return None
        m = self.registry.get(slo.sli["metric"])
        if not isinstance(m, Histogram):
            return None
        for edge in m.buckets:
            if edge >= slo.sli["threshold"]:
                return edge
        return None

    # ------------------------------------------------------------ sampling
    def sample(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        cum = {s.name: self._cumulative(s) for s in self.slos}
        with self._lock:
            self._samples.append(_Sample(now, cum))
            # keep one sample older than the history horizon so the longest
            # window always has a baseline to subtract from
            while (len(self._samples) > 2
                   and now - self._samples[1].t > self._history_s):
                self._samples.popleft()

    def _baseline(self, floor_t: float) -> _Sample:
        """The earliest sample at/after ``floor_t`` (fallback: earliest) —
        under-covering a window beats inventing pre-history."""
        with self._lock:
            for s in self._samples:
                if s.t >= floor_t:
                    return s
            return self._samples[0]

    def _latest(self) -> _Sample:
        with self._lock:
            return self._samples[-1]

    # ---------------------------------------------------------- evaluation
    @staticmethod
    def _delta(cur: Tuple[float, float],
               past: Tuple[float, float]) -> Tuple[float, float]:
        # clamp: a swapped engine restarts its counters; a negative delta
        # would report phantom good events
        return (max(0.0, cur[0] - past[0]), max(0.0, cur[1] - past[1]))

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Every SLO over its spec windows, from live cumulative values
        against the sample ring. ``compliant`` follows the multi-window
        burn-rate rule: breaching only when EVERY populated window burns
        faster than budget."""
        now = time.monotonic() if now is None else now
        out = []
        for slo in self.slos:
            cur = self._cumulative(slo)
            windows = []
            for w in slo.windows_s:
                base = self._baseline(now - w)
                good, total = self._delta(cur, base.cumulative.get(
                    slo.name, (0.0, 0.0)))
                entry = evaluate_window(good, total, slo.objective)
                # covered_s is HONEST, not capped at the window: with no
                # sampler the baseline is the time-zero sample, and a
                # "300s window" actually covering two hours must say so
                entry.update(window_s=w, covered_s=round(now - base.t, 3))
                windows.append(entry)
            populated = [w for w in windows if not w["no_data"]]
            breaching = bool(populated) and all(
                w["burn_rate"] > 1.0 for w in populated)
            # budget remaining over the longest populated window
            budget = None
            if populated:
                budget = round(max(0.0, 1.0 - populated[-1]["burn_rate"]), 4)
            doc = {
                "name": slo.name,
                "objective": slo.objective,
                "description": slo.description,
                "sli": dict(slo.sli),
                "windows": windows,
                "compliant": not breaching,
                "budget_remaining": budget,
                "no_data": not populated,
            }
            thr = self.effective_threshold(slo)
            if thr is not None:
                doc["threshold_effective"] = thr
            out.append(doc)
        with self._lock:
            self._last_results = out
        return out

    def verdicts(self, since_t: Optional[float] = None) -> List[dict]:
        """One window per SLO: from the most recent sample (or the earliest
        sample at/after ``since_t``) to NOW. ``compliant`` here is the
        strict single-window rule — compliance >= objective — because the
        caller chose the window to BE the judgment period (a promotion
        stage, a whole replay run)."""
        now = time.monotonic()
        base = (self._latest() if since_t is None
                else self._baseline(since_t))
        out = []
        for slo in self.slos:
            cur = self._cumulative(slo)
            good, total = self._delta(
                cur, base.cumulative.get(slo.name, (0.0, 0.0)))
            entry = evaluate_window(good, total, slo.objective)
            entry.update(name=slo.name, objective=slo.objective,
                         window_s=round(now - base.t, 3))
            thr = self.effective_threshold(slo)
            if thr is not None:
                entry["threshold_effective"] = thr
            out.append(entry)
        return out

    # ------------------------------------------------------------- gauges
    def restate_gauges(self, results: Optional[List[dict]] = None) -> None:
        """State the dtx_slo_* series from the given (default: last)
        evaluation. Each gauge's series set is swapped ATOMICALLY
        (Metric.replace) so a scrape racing a restate — or two restaters
        racing each other — sees a complete old or new set, never a
        half-cleared one."""
        if results is None:
            with self._lock:
                results = list(self._last_results)
        if not results:
            return
        g = self.registry.gauge
        objective = g("dtx_slo_objective",
                      "Declared objective per SLO (good events / total).")
        compliance = g("dtx_slo_compliance",
                       "Measured compliance over each SLO's longest "
                       "populated window (1.0 when no data).")
        burn = g("dtx_slo_burn_rate",
                 "Error-budget burn rate per evaluation window (1.0 = "
                 "burning exactly at the objective's rate).")
        budget = g("dtx_slo_error_budget_remaining",
                   "Fraction of the error budget left over the longest "
                   "populated window (0 = budget spent).")
        compliant = g("dtx_slo_compliant",
                      "1 unless every populated window burns budget "
                      "faster than 1.0 (the multi-window page condition).")
        objective_v, compliance_v, burn_v, budget_v, compliant_v = \
            [], [], [], [], []
        for doc in results:
            labels = {"slo": doc["name"]}
            objective_v.append((labels, doc["objective"]))
            compliant_v.append((labels, 0 if not doc["compliant"] else 1))
            populated = [w for w in doc["windows"] if not w["no_data"]]
            compliance_v.append(
                (labels, populated[-1]["compliance"] if populated else 1.0))
            if doc["budget_remaining"] is not None:
                budget_v.append((labels, doc["budget_remaining"]))
            for w in doc["windows"]:
                if not w["no_data"]:
                    burn_v.append(({"slo": doc["name"],
                                    "window": f"{int(w['window_s'])}s"},
                                   w["burn_rate"]))
        objective.replace(objective_v)
        compliance.replace(compliance_v)
        burn.replace(burn_v)
        budget.replace(budget_v)
        compliant.replace(compliant_v)

    # -------------------------------------------------------------- report
    def report(self, plane: str = "") -> dict:
        """The /debug/slo body: sample, evaluate, restate, summarize."""
        self.sample()
        results = self.evaluate()
        self.restate_gauges(results)
        return {
            "plane": plane,
            "compliant": all(d["compliant"] for d in results),
            "slos": results,
        }

    # ---------------------------------------------------------- background
    def start(self, interval_s: float = 15.0) -> None:
        """Background sampler: keeps the spec windows populated without a
        /debug/slo poller. Samples ONLY — gauges are restated by the
        scrape/report paths, which serialize under their own locks.
        Idempotent."""
        if self._thread is not None or interval_s <= 0:
            return
        def _loop():
            while not self._shutdown.wait(interval_s):
                try:
                    self.sample()
                except Exception:  # noqa: BLE001 — sampling must not die
                    pass
        self._thread = threading.Thread(
            target=_loop, name="dtx-slo-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def violations(verdict_list: List[dict]) -> List[str]:
    """Human-readable violation lines from ``verdicts()`` output — the
    replay epilogue's exit message and the promotion guard's rollback
    reason both come from here, so a violated objective is always NAMED."""
    out = []
    for v in verdict_list:
        if v.get("no_data") or v.get("compliant", True):
            continue
        out.append(
            f"SLO {v['name']} violated: compliance "
            f"{v['compliance']:.4f} < objective {v['objective']:g} "
            f"over {v['total']} events in {v['window_s']:.1f}s")
    return out
