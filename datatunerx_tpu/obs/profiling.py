"""On-demand JAX profiler capture behind ``POST /debug/profile``.

The training loop already self-profiles a step window (tuning/train.py,
``--profile_steps``); serving had nothing — diagnosing a TPOT regression on
a live replica meant restarting it under a profiler. This module arms
``jax.profiler`` for an N-second window on request: the serving server
captures its own process (the engine's decode/prefill ticks are labeled via
``jax.profiler.TraceAnnotation``, same as PR 3's pipeline annotations), and
the gateway passes the request through to a replica.

One capture at a time per process — ``jax.profiler.start_trace`` is
process-global state, so a second concurrent request is refused (409 at the
HTTP layer) rather than corrupting the active trace.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Optional

MAX_SECONDS = 120.0


def resolve_profile_dir(requested: Optional[str] = None) -> str:
    """Resolve a capture directory, confined under the allowed root.

    /debug/profile is unauthenticated on the serving port (like /admin/*,
    it trusts the operator network) — but a requested ``dir`` must not turn
    into arbitrary filesystem writes. Paths resolve under the root
    (``DTX_PROFILE_DIR``, default the system tempdir): relative requests
    join it, absolute requests must already lie inside it; anything
    escaping raises ValueError (a client error, not a server fault).
    No request → a fresh ``dtx-profile-*`` tempdir under the root."""
    base = os.path.realpath(
        os.environ.get("DTX_PROFILE_DIR") or tempfile.gettempdir())
    if not requested:
        os.makedirs(base, exist_ok=True)
        return tempfile.mkdtemp(prefix="dtx-profile-", dir=base)
    path = os.path.realpath(os.path.join(base, requested))
    if path != base and not path.startswith(base + os.sep):
        raise ValueError(
            f"profile dir {requested!r} escapes the allowed root {base!r} "
            "(set DTX_PROFILE_DIR to change it)")
    return path


class Profiler:
    """One-at-a-time background profiler window. ``start`` returns the
    EFFECTIVE window length (the request clamped to [0.05, MAX_SECONDS] —
    callers echo this, not the raw request, so an operator never waits on
    a 600s window that actually stopped at 120), or None when a capture is
    already running; the worker thread stops the trace after the window
    elapses (or earlier on ``close``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._cancel = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, log_dir: str, seconds: float) -> Optional[float]:
        seconds = min(max(float(seconds), 0.05), MAX_SECONDS)
        with self._lock:
            if self._active is not None:
                return None
            self._active = {"dir": log_dir, "seconds": seconds,
                            "started": time.time()}
            self._cancel.clear()
        os.makedirs(log_dir, exist_ok=True)
        import jax

        try:
            jax.profiler.start_trace(log_dir)
        except Exception:
            with self._lock:
                self._active = None
            raise
        self._thread = threading.Thread(
            target=self._window, args=(seconds,),
            name="dtx-profile-window", daemon=True)
        self._thread.start()
        return seconds

    def _window(self, seconds: float):
        self._cancel.wait(timeout=seconds)
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a failed stop must not leak state
            pass
        with self._lock:
            self._active = None

    def status(self) -> Optional[dict]:
        with self._lock:
            return dict(self._active) if self._active else None

    def close(self):
        """Cancel an in-flight window and join the worker (shutdown path)."""
        self._cancel.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None


_PROFILER = Profiler()


def process_profiler() -> Profiler:
    """The process-wide profiler (jax.profiler state is process-global)."""
    return _PROFILER
