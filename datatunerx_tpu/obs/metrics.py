"""Shared Prometheus metrics registry + correct text exposition.

One registry implementation for every plane — the gateway, the serving
server, and the training MetricsLogger all build their /metrics (or
``watch/metrics.prom``) exposition from the classes here, so the format
invariants the scraper relies on hold everywhere: one # TYPE line per
metric name preceding all its samples, no duplicate series, label values
escaped per the exposition spec (backslash, double-quote, newline).

Grew out of ``gateway/metrics.py`` (PR 2), which now re-exports from here;
the serving server's hand-assembled exposition lines and the training
logger's jsonl-only path both migrate onto this registry in PR 7.

Hot-path discipline (dtxlint DTX001): ``Histogram.observe`` and
``Metric.inc`` never convert device values — callers observe plain host
floats that already crossed at a designed sync point (token arrival on
the engine's host queue, a perf_counter delta). Recording is a short
uncontended lock around dict/int arithmetic; exposition (the expensive
string work) happens only at scrape time.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, float("inf"))

# Millisecond-scale buckets for the serving latency histograms
# (dtx_serving_ttft_ms / dtx_serving_tpot_ms / dtx_gateway_queue_wait_ms /
# dtx_serving_prefill_chunk_ms). Spans sub-ms decode ticks on a warm TPU up
# to multi-second cold prefills; fixed edges so replicas aggregate.
MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 2500.0, 5000.0, 10000.0, 30000.0, float("inf"))


def sample_percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over RAW samples (0.0 on empty) — the one
    implementation for every rolling-window quantile (replica outcome
    windows, the prefetch advisory). ``Histogram.percentile`` stays the
    bucketed flavor for exported histograms; this is for in-memory sample
    lists where exactness is free."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
    return s[idx]


def annotation_start(line: str) -> int:
    """Index where a `` # …`` annotation tail (exemplar or unknown) begins
    on an exposition line, QUOTE-AWARE — a ``' # '`` inside a label value
    is data, not an annotation. -1 when the line has none. The ONE scanner
    shared by the gateway's replica scrape parser and the test/lint
    exposition parser, so the two can't drift on the grammar."""
    in_quotes = False
    i = 0
    n = len(line)
    while i < n:
        c = line[i]
        if in_quotes:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == "#" and i >= 1 and line[i - 1] == " ":
            return i - 1
        i += 1
    return -1


def exemplars_requested(path: str) -> bool:
    """Did the HTTP request path opt in to exemplar annotations with an
    exact ``exemplars=1`` query parameter? Parsed, not substring-matched:
    ``?no_exemplars=1`` must NOT enable the classic-parser-breaking tails."""
    from urllib.parse import parse_qs, urlsplit

    q = parse_qs(urlsplit(path or "").query)
    return q.get("exemplars", ["0"])[-1] == "1"


def escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def format_sample(name: str, labels: Optional[dict], value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


class Metric:
    def __init__(self, name: str, mtype: str, help_text: str = ""):
        self.name = name
        self.mtype = mtype
        self.help_text = help_text
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def _key(self, labels: Optional[dict]):
        return tuple(sorted((labels or {}).items()))

    def inc(self, labels: Optional[dict] = None, by: float = 1.0):
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0.0) + by

    def set(self, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def get(self, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    def series(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Snapshot of every series (label-tuple key → value) — the SLO
        evaluator samples counters through this instead of groping
        ``_series`` under someone else's lock discipline."""
        with self._lock:
            return dict(self._series)

    def clear(self):
        """Drop all series (per-replica gauges are re-stated each scrape so
        removed replicas don't linger as stale series)."""
        with self._lock:
            self._series.clear()

    def replace(self, values: "Sequence[Tuple[Optional[dict], float]]"):
        """Swap the FULL series set atomically ([(labels, value), …]) — the
        restate-at-sample-time path (SLO gauges) uses this instead of
        clear()+set() so a concurrent expose() sees either the old or the
        new complete set, never a half-restated one."""
        new = {self._key(labels): float(v) for labels, v in values}
        with self._lock:
            self._series = new

    def expose(self) -> List[str]:
        lines = []
        if self.help_text:
            lines.append(f"# HELP {self.name} {self.help_text}")
        lines.append(f"# TYPE {self.name} {self.mtype}")
        with self._lock:
            for key, value in sorted(self._series.items()):
                fv = int(value) if float(value).is_integer() else value
                lines.append(format_sample(self.name, dict(key), fv))
        return lines


class Histogram:
    """Cumulative-bucket histogram (classic Prometheus shape).

    ``observe(value, trace_id=...)`` additionally keeps the LAST exemplar
    per bucket — an OpenMetrics-style ``# {trace_id="dtx-…"} value ts``
    annotation on the bucket line — so a p99 bucket links straight to the
    request trace behind it (``GET /debug/trace/<id>``). With no trace id
    the observe path is byte-identical to before: no allocation, no extra
    branch work beyond one falsy check."""

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        self.name = name
        self.help_text = help_text
        self.buckets = tuple(buckets)
        if self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._total = 0
        # bucket index → (trace_id, observed value, unix ts); populated
        # lazily — a histogram that never sees a trace id never pays for it
        self._exemplars: Dict[int, Tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, trace_id: Optional[str] = None):
        with self._lock:
            self._sum += value
            self._total += 1
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    self._counts[i] += 1
                    if trace_id:
                        self._exemplars[i] = (trace_id, value, time.time())
                    break

    def percentile(self, q: float) -> float:
        """Approximate quantile from bucket upper edges (the autoscale
        signal's p95; the +inf bucket reports the largest finite edge)."""
        with self._lock:
            if self._total == 0:
                return 0.0
            target = q * self._total
            run = 0
            for i, edge in enumerate(self.buckets):
                run += self._counts[i]
                if run >= target:
                    if edge == float("inf"):
                        return self.buckets[-2] if len(self.buckets) > 1 else 0.0
                    return edge
            return self.buckets[-2]

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Snapshot of (upper edge, CUMULATIVE count) pairs plus implicit
        total — the SLO evaluator's windowed good/total deltas come from
        subtracting two of these."""
        with self._lock:
            out = []
            cumulative = 0
            for i, edge in enumerate(self.buckets):
                cumulative += self._counts[i]
                out.append((edge, cumulative))
            return out

    def exemplars(self) -> Dict[float, Tuple[str, float, float]]:
        """Upper edge → (trace_id, observed value, unix ts) for every bucket
        holding an exemplar."""
        with self._lock:
            return {self.buckets[i]: ex for i, ex in self._exemplars.items()}

    def expose(self, with_exemplars: bool = True) -> List[str]:
        """``with_exemplars=False`` emits the classic 0.0.4 exposition.
        The HTTP servers default the WIRE to False (an exemplar tail is a
        parse error to a classic Prometheus parser, which would fail the
        whole scrape) and include exemplars only on the explicit
        ``/metrics?exemplars=1`` debug view."""
        lines = []
        if self.help_text:
            lines.append(f"# HELP {self.name} {self.help_text}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            cumulative = 0
            for i, edge in enumerate(self.buckets):
                cumulative += self._counts[i]
                le = "+Inf" if edge == float("inf") else repr(edge)
                line = format_sample(
                    f"{self.name}_bucket", {"le": le}, cumulative)
                ex = self._exemplars.get(i) if with_exemplars else None
                if ex is not None:
                    tid, val, ts = ex
                    line += (f' # {{trace_id="{escape_label_value(tid)}"}} '
                             f"{val} {round(ts, 3)}")
                lines.append(line)
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._total}")
        return lines


class Registry:
    def __init__(self):
        self._metrics: "Dict[str, object]" = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Metric:
        return self._register(name, "counter", help_text)

    def gauge(self, name: str, help_text: str = "") -> Metric:
        return self._register(name, "gauge", help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_text, buckets)
                self._metrics[name] = m
            return m

    def _register(self, name: str, mtype: str, help_text: str) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Metric(name, mtype, help_text)
                self._metrics[name] = m
            return m

    def get(self, name: str):
        """The registered metric object, or None — for read-only consumers
        (the SLO evaluator) that must not implicitly declare a series just
        by asking about it."""
        with self._lock:
            return self._metrics.get(name)

    def expose(self, with_exemplars: bool = True) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if isinstance(m, Histogram):
                lines.extend(m.expose(with_exemplars=with_exemplars))
            else:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


def serving_latency_histograms(
        registry: Registry) -> Tuple[Histogram, Histogram, Histogram]:
    """The serving plane's (ttft, tpot, prefill_chunk) histograms,
    declared ONCE here: the engine records into them and the serving
    server pre-declares them at scrape time, and Registry keeps the first
    registration — two call sites with their own HELP text would make the
    exposition depend on whether the first scrape beats the engine load."""
    return (
        registry.histogram(
            "dtx_serving_ttft_ms",
            "Per-request time to first streamed token (queue + prefill + "
            "first decode chunk).", buckets=MS_BUCKETS),
        registry.histogram(
            "dtx_serving_tpot_ms",
            "Per-request mean inter-token time after the first token.",
            buckets=MS_BUCKETS),
        registry.histogram(
            "dtx_serving_prefill_chunk_ms",
            "Wall time per chunked-prefill program as seen by the "
            "scheduler (dispatch + any queue drain on async backends).",
            buckets=MS_BUCKETS),
    )


def adapter_load_histogram(registry: Registry) -> Histogram:
    """The adapter plane's load-latency histogram (checkpoint read +
    pad + pool insert on a load-on-miss), declared once here for the same
    reason as ``serving_latency_histograms``: the engine's registry
    observer and the serving server's scrape-time pre-declaration must
    share one object."""
    return registry.histogram(
        "dtx_serving_adapter_load_ms",
        "Wall time to materialise an adapter into a pool slot "
        "(checkpoint load + rank-pad + device insert) on a load-on-miss.",
        buckets=MS_BUCKETS)


def spec_accept_len_histogram(registry: Registry) -> Histogram:
    """Accepted-draft-length histogram of the speculative decode plane
    (``dtx_serving_spec_accept_len``): one observation per drafting row per
    verify step, value = tokens of the proposal prefix the target accepted
    (0..k). Declared once here — the engine observes into it and the
    serving server pre-declares it at scrape time — like
    ``serving_latency_histograms``. Buckets are token counts, not time, so
    no unit suffix."""
    return registry.histogram(
        "dtx_serving_spec_accept_len",
        "Draft tokens accepted per verify-k step (before the corrected/"
        "bonus token).", buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))


# ------------------------------------------------------------ process plumbing

_PROCESS_START = time.monotonic()


def set_build_info(registry: Registry, plane: str):
    """State the ``dtx_build_info`` gauge: value 1, the interesting bits in
    labels (the node_exporter idiom — joinable against any other series)."""
    from datatunerx_tpu import __version__

    registry.gauge(
        "dtx_build_info",
        "Build/version identity; value is always 1, the payload is the "
        "labels.").set(1, {"version": __version__, "plane": plane})


def set_uptime(registry: Registry, plane: str,
               started_at: Optional[float] = None):
    """Re-state the per-plane uptime gauge (call at scrape time).
    ``started_at`` is a ``time.monotonic()`` stamp; default = process start."""
    t0 = _PROCESS_START if started_at is None else started_at
    registry.gauge(
        f"dtx_{plane}_uptime_seconds",
        "Seconds since this server process started.").set(
        round(time.monotonic() - t0, 3))
