"""Unified observability plane (stdlib-only, dtxlint house style).

Three pieces, one story — see what every plane of the platform is doing:

  obs.metrics    — the shared Prometheus registry (counters / gauges /
                   fixed-bucket histograms, one exposition encoder) behind
                   every /metrics endpoint and the training logger's
                   ``watch/metrics.prom``.
  obs.trace      — Dapper-style spans over the gateway's X-DTX-Trace-Id:
                   context-propagated tracer, bounded trace ring behind
                   ``GET /debug/trace/<id>``, and the engine bridge that
                   folds scheduler timelines into per-request spans with
                   true TTFT/TPOT.
  obs.profiling  — on-demand N-second ``jax.profiler`` windows behind
                   ``POST /debug/profile`` (serving + gateway passthrough).
  obs.slo        — declarative objectives over the registry's histograms
                   and counters: multi-window burn-rate evaluation behind
                   ``GET /debug/slo``, shared by the promotion guard and
                   the load-replay epilogue.
"""

from datatunerx_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    MS_BUCKETS,
    Histogram,
    Metric,
    Registry,
    serving_latency_histograms,
    set_build_info,
    set_uptime,
)
from datatunerx_tpu.obs.profiling import Profiler, process_profiler  # noqa: F401
from datatunerx_tpu.obs.slo import (  # noqa: F401
    SLO,
    SLOEvaluator,
    default_slos,
    load_slos,
    parse_slos,
    violations,
)
from datatunerx_tpu.obs.trace import (  # noqa: F401
    Span,
    Tracer,
    TraceStore,
    build_request_span,
)
