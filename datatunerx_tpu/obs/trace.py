"""Request tracing: Dapper-style spans over the X-DTX-Trace-Id the gateway
already mints.

The gateway has propagated ``X-DTX-Trace-Id`` since PR 2, but the id was
write-only — nothing collected what happened under it. This module makes it
a real trace:

  Span        — one timed operation: name, trace id, wall-clock start, a
                monotonic duration, attrs, and point-in-time events (offsets
                from span start). Spans serialize to plain dicts so they
                cross process boundaries as JSON (the gateway merges a
                remote replica's spans into its own trace view).
  Tracer      — context-propagated span factory (``contextvars``): nested
                ``with tracer.span(...)`` blocks get their parent linked
                automatically, completed spans land in the TraceStore, and
                orphans (opened but never closed — a handler thread died)
                are reaped with status "orphaned" instead of leaking.
  TraceStore  — bounded ring of completed traces keyed by trace id, behind
                ``GET /debug/trace/<id>`` on both servers; optional JSONL
                event log for offline forensics.

Hot-path discipline: span creation/finish is a couple of perf_counter reads
plus appends; the store insert is a short lock around an OrderedDict move.
Nothing here touches device values — timeline stamps are taken at the
engine's designed sync points and arrive as host floats
(``build_request_span``).
"""

from __future__ import annotations

import collections
import contextvars
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_CURRENT_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "dtx_current_span", default=None)


class Span:
    """One timed operation inside a trace. Mutated by the thread that owns
    the request (no lock — a span never migrates threads mid-flight)."""

    __slots__ = ("name", "trace_id", "parent", "attrs", "events",
                 "start_ms", "_t0", "duration_ms", "status", "_token")

    def __init__(self, name: str, trace_id: str = "",
                 parent: Optional[str] = None,
                 attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.parent = parent
        self.attrs = dict(attrs or {})
        self.events: List[dict] = []
        self.start_ms = time.time() * 1e3  # wall, for cross-process ordering
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.status = "open"
        self._token = None

    def event(self, name: str, **attrs):
        """Point-in-time annotation at the current offset from span start."""
        e = {"name": name,
             "t_ms": round((time.perf_counter() - self._t0) * 1e3, 3)}
        if attrs:
            e.update(attrs)
        self.events.append(e)

    def set(self, **attrs):
        self.attrs.update(attrs)

    def finish(self, status: str = "ok"):
        if self.duration_ms is None:
            self.duration_ms = round(
                (time.perf_counter() - self._t0) * 1e3, 3)
            self.status = status

    def age_s(self) -> float:
        return time.perf_counter() - self._t0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "parent": self.parent,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": self.duration_ms,
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _SpanContext:
    """Context manager returned by ``Tracer.span``: installs the span as
    the contextvar parent for the block, finishes + records it on exit."""

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._span._token = _CURRENT_SPAN.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if self._span._token is not None:
            _CURRENT_SPAN.reset(self._span._token)
            self._span._token = None
        if exc is not None and "error" not in self._span.attrs:
            self._span.attrs["error"] = str(exc)
        self._tracer.finish(
            self._span, status="error" if exc_type is not None else "ok")
        return False


class TraceStore:
    """Bounded ring buffer of completed traces keyed by trace id.

    ``add`` appends a completed span to its trace and bumps the trace to
    the ring's MRU end; when the ring exceeds ``capacity`` traces, the
    oldest trace is dropped whole. With ``jsonl_path`` set, every completed
    span is also appended (one JSON object per line) — the write happens
    OUTSIDE the ring lock so a slow disk can't stall recording threads."""

    def __init__(self, capacity: int = 256,
                 jsonl_path: Optional[str] = None,
                 max_spans_per_trace: int = 64):
        self.capacity = max(1, int(capacity))
        self.max_spans_per_trace = max_spans_per_trace
        self.jsonl_path = jsonl_path
        self.evictions = 0
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._jsonl_lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._traces)

    def add(self, span_dict: dict):
        tid = span_dict.get("trace_id") or ""
        if not tid:
            return
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                spans = self._traces[tid] = []
            if len(spans) < self.max_spans_per_trace:
                spans.append(span_dict)
            self._traces.move_to_end(tid)
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
                self.evictions += 1
        if self.jsonl_path:
            line = json.dumps(span_dict, default=str)
            with self._jsonl_lock:
                with open(self.jsonl_path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                return None
            return {"trace_id": trace_id, "spans": list(spans)}

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._traces.keys())


class Tracer:
    """Span factory + open-span registry (for orphan reaping).

    ``with tracer.span("gateway.request", trace_id=tid) as sp:`` opens a
    span whose parent is whatever span the calling context already holds;
    on block exit the span is finished and recorded into the store. A span
    opened but never closed (its owning thread died mid-request) is closed
    with status "orphaned" by ``reap_orphans`` — invoked opportunistically
    on span creation, so the open-set cannot grow without bound."""

    _REAP_EVERY_S = 30.0

    def __init__(self, store: Optional[TraceStore] = None,
                 orphan_age_s: float = 600.0):
        # NOT `store or ...`: an EMPTY TraceStore is falsy through __len__,
        # and silently swapping the caller's store for a private one breaks
        # the /debug/trace endpoint reading the shared ring
        self.store = store if store is not None else TraceStore()
        self.orphan_age_s = orphan_age_s
        self._open: Dict[int, Span] = {}
        self._lock = threading.Lock()
        self._last_reap = time.perf_counter()

    def span(self, name: str, trace_id: str = "",
             **attrs) -> _SpanContext:
        parent = _CURRENT_SPAN.get()
        if parent is not None and not trace_id:
            trace_id = parent.trace_id
        sp = Span(name, trace_id=trace_id,
                  parent=parent.name if parent is not None else None,
                  attrs=attrs)
        with self._lock:
            self._open[id(sp)] = sp
        self._maybe_reap()
        return _SpanContext(self, sp)

    def start(self, name: str, trace_id: str = "",
              parent: Optional[str] = None, **attrs) -> Span:
        """Open a span WITHOUT contextvar propagation — for generators,
        where a ``with tracer.span(...)`` block suspending across yields
        would leak the contextvar into the consumer's context. The caller
        owns the lifecycle: pair with ``tracer.finish(span)``."""
        sp = Span(name, trace_id=trace_id, parent=parent, attrs=attrs)
        with self._lock:
            self._open[id(sp)] = sp
        self._maybe_reap()
        return sp

    def current(self) -> Optional[Span]:
        return _CURRENT_SPAN.get()

    def finish(self, sp: Span, status: str = "ok"):
        with self._lock:
            was_open = self._open.pop(id(sp), None) is not None
        sp.finish(status)
        # record only if WE closed it: a span the reaper already recorded as
        # "orphaned" (request outlived orphan_age_s, then completed anyway)
        # must not land in the trace a second time
        if was_open:
            self.store.add(sp.to_dict())

    # ------------------------------------------------------------- orphans
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def _maybe_reap(self):
        now = time.perf_counter()
        if now - self._last_reap < self._REAP_EVERY_S:
            return
        self._last_reap = now
        self.reap_orphans()

    def reap_orphans(self, max_age_s: Optional[float] = None) -> int:
        """Close-and-record every open span older than ``max_age_s`` with
        status "orphaned". Returns how many were reaped."""
        limit = self.orphan_age_s if max_age_s is None else max_age_s
        with self._lock:
            stale = [sp for sp in self._open.values() if sp.age_s() > limit]
            for sp in stale:
                self._open.pop(id(sp), None)
        for sp in stale:
            sp.finish("orphaned")
            self.store.add(sp.to_dict())
        return len(stale)


# ------------------------------------------------------------ engine bridge

def build_request_span(
    trace_id: str,
    t_submit: float,
    timeline: List[Tuple[float, str, dict]],
    first_token_ts: Optional[float],
    last_token_ts: Optional[float],
    n_tokens: int,
    wall_submit_ms: float,
    name: str = "engine.request",
    error: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> dict:
    """Fold an engine request's scheduler timeline into one span dict.

    ``timeline`` entries are ``(perf_counter stamp, event name, detail)``
    recorded by the scheduler (admit / prefill / activate / finish);
    ``first/last_token_ts`` are the host arrival stamps of the first and
    last streamed tokens — taken at the decode loop's designed sync point,
    so the derived per-request TTFT/TPOT are true wall numbers:

      ttft_ms = first_token - submit        (queue + prefill + first decode)
      tpot_ms = (last - first) / (n - 1)    (steady-state inter-token time)
    """
    events = [{"name": ev, "t_ms": round((ts - t_submit) * 1e3, 3), **det}
              for ts, ev, det in timeline]
    out_attrs = dict(attrs or {})
    out_attrs["n_tokens"] = n_tokens
    end_ts = t_submit
    if first_token_ts is not None:
        events.append({"name": "first_token",
                       "t_ms": round((first_token_ts - t_submit) * 1e3, 3)})
        out_attrs["ttft_ms"] = round((first_token_ts - t_submit) * 1e3, 3)
        end_ts = first_token_ts
    if last_token_ts is not None:
        end_ts = last_token_ts
        if first_token_ts is not None and n_tokens > 1:
            out_attrs["tpot_ms"] = round(
                (last_token_ts - first_token_ts) / (n_tokens - 1) * 1e3, 3)
    if timeline:
        end_ts = max(end_ts, timeline[-1][0])
    if error:
        out_attrs["error"] = error
    events.sort(key=lambda e: e["t_ms"])
    return {
        "name": name,
        "trace_id": trace_id,
        "parent": None,
        "start_ms": round(wall_submit_ms, 3),
        "duration_ms": round((end_ts - t_submit) * 1e3, 3),
        "status": "error" if error else "ok",
        "attrs": out_attrs,
        "events": events,
    }
