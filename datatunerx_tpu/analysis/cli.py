"""dtxlint command line: ``dtxlint``, ``dtx lint``, ``python -m
datatunerx_tpu.analysis``.

Exit codes: 0 = clean (or everything suppressed/baselined), 1 = new
findings, 2 = usage error. ``--format json`` emits one machine-readable
object for CI annotation tooling; ``--write-baseline`` records the
current findings as accepted debt instead of failing on them.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from datatunerx_tpu.analysis import baseline as baseline_mod
from datatunerx_tpu.analysis.config import LintConfig, load_config
from datatunerx_tpu.analysis.core import LintResult, lint_paths
from datatunerx_tpu.analysis.rules import RULE_CLASSES, all_rules, rules_by_id

_SEVERITY_RANK = {"warning": 0, "error": 1}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtxlint",
        description="JAX-aware static analysis for datatunerx-tpu "
                    "(host-sync, retrace, sharding, lock-discipline rules)")
    p.add_argument("paths", nargs="*", default=["datatunerx_tpu"],
                   help="files/directories to lint (default: datatunerx_tpu)")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--select", default="",
                   help="comma list of rule ids to run (default: all)")
    p.add_argument("--baseline", default="",
                   help="baseline file (default: [tool.dtxlint] baseline)")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as accepted debt and exit 0")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file (report everything)")
    p.add_argument("--no-config", action="store_true",
                   help="skip pyproject [tool.dtxlint] discovery")
    p.add_argument("--fail-on", choices=["warning", "error"],
                   default="warning",
                   help="minimum severity that fails the run "
                        "(default: warning — everything gates)")
    p.add_argument("--list-rules", action="store_true")
    return p


def _list_rules() -> int:
    for cls in RULE_CLASSES:
        doc = (cls.__module__ and sys.modules[cls.__module__].__doc__) or ""
        first = next((ln.strip() for ln in doc.splitlines() if cls.id in ln),
                     "")
        print(f"{cls.id}  {cls.name:28s} [{cls.severity}] {first}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    if args.no_config:
        config = LintConfig()
    else:
        config = load_config(start=args.paths[0] if args.paths else ".")
    if args.select:
        wanted = [r.strip() for r in args.select.split(",") if r.strip()]
        known = {cls.id for cls in RULE_CLASSES}
        unknown = sorted(set(wanted) - known)
        if unknown:
            # a typo must not turn the gate green by selecting zero rules
            print(f"dtxlint: unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        rules = rules_by_id(wanted)
    else:
        rules = all_rules()

    result: LintResult = lint_paths(args.paths, config=config, rules=rules)

    baseline_path = args.baseline or config.resolve(config.baseline)
    if args.write_baseline:
        baseline_mod.save_baseline(baseline_path, result.findings)
        print(f"dtxlint: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    carried = (baseline_mod.load_baseline(baseline_path)
               if not args.no_baseline else baseline_mod.load_baseline(""))
    new, baselined = baseline_mod.partition(result.findings, carried)
    gate = [f for f in new
            if _SEVERITY_RANK.get(f.severity, 1)
            >= _SEVERITY_RANK[args.fail_on]]

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "suppressed": result.suppressed,
            "files": result.files,
            "failed": bool(gate),
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        summary = (f"dtxlint: {len(new)} finding(s) in {result.files} "
                   f"file(s)")
        extras = []
        if result.suppressed:
            extras.append(f"{result.suppressed} suppressed inline")
        if baselined:
            extras.append(f"{len(baselined)} baselined")
        if extras:
            summary += " (" + ", ".join(extras) + ")"
        print(summary)
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
