"""dtxlint command line: ``dtxlint``, ``dtx lint``, ``python -m
datatunerx_tpu.analysis``.

Exit codes: 0 = clean (or everything suppressed/baselined), 1 = new
findings (or, with ``--fix --check``, fixes that would change files),
2 = usage error. ``--format json`` emits one machine-readable object
(schema ``version`` 2) for CI annotation tooling; ``--write-baseline``
records the current findings as accepted debt instead of failing.

By default linting is PROGRAM-LEVEL: the cross-module call graph over
the linted package lets DTX001/DTX007/DTX009 follow calls across files,
with per-module summaries cached on mtime+size (``--no-program`` /
``--no-cache`` opt out). ``--changed`` restricts to files differing
from git HEAD for cheap pre-commit runs; ``--fix`` applies the
mechanical autofixes (DTX002 hoist-jit-out-of-loop, DTX008
default-argument deferral) and ``--fix --check`` is the CI idempotency
gate — it fails if a fix is still applicable, without writing.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
from typing import List, Optional

from datatunerx_tpu.analysis import baseline as baseline_mod
from datatunerx_tpu.analysis.config import LintConfig, load_config
from datatunerx_tpu.analysis.core import LintResult, lint_paths
from datatunerx_tpu.analysis.rules import RULE_CLASSES, all_rules, rules_by_id

_SEVERITY_RANK = {"warning": 0, "error": 1}
JSON_SCHEMA_VERSION = 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtxlint",
        description="JAX-aware static analysis for datatunerx-tpu "
                    "(host-sync, retrace, sharding, lock-discipline, "
                    "donation rules; program-level cross-module graph)")
    p.add_argument("paths", nargs="*", default=["datatunerx_tpu"],
                   help="files/directories to lint (default: datatunerx_tpu)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: one object with schema `version`, findings, "
                        "and counts")
    p.add_argument("--select", default="",
                   help="comma list of rule ids to run (default: all)")
    p.add_argument("--baseline", default="",
                   help="baseline file (default: [tool.dtxlint] baseline)")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as accepted debt and exit 0")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file (report everything)")
    p.add_argument("--no-config", action="store_true",
                   help="skip pyproject [tool.dtxlint] discovery")
    p.add_argument("--fail-on", choices=["warning", "error"],
                   default="warning",
                   help="minimum severity that fails the run "
                        "(default: warning — everything gates)")
    p.add_argument("--no-program", action="store_true",
                   help="per-module rules only: skip the cross-module "
                        "program pass (DTX001/DTX007/DTX009 stop at file "
                        "boundaries)")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and don't write the module-summary cache "
                        "([tool.dtxlint] cache, keyed on file mtime+size)")
    p.add_argument("--changed", action="store_true",
                   help="lint only files differing from git HEAD "
                        "(`git diff --name-only HEAD`) — cheap pre-commit "
                        "mode; the program graph covers just those files")
    p.add_argument("--fix", action="store_true",
                   help="apply automatic fixes for the mechanical rules "
                        "(DTX002 hoist-jit-out-of-loop, DTX008 "
                        "default-argument deferral), re-lint to verify, "
                        "then report what remains")
    p.add_argument("--check", action="store_true",
                   help="with --fix: write nothing, exit 1 if any fix "
                        "would be applied (CI idempotency gate)")
    p.add_argument("--list-rules", action="store_true")
    return p


def _list_rules() -> int:
    for cls in RULE_CLASSES:
        doc = (cls.__module__ and sys.modules[cls.__module__].__doc__) or ""
        first = next((ln.strip() for ln in doc.splitlines() if cls.id in ln),
                     "")
        print(f"{cls.id}  {cls.name:28s} [{cls.severity}] {first}")
    return 0


def _changed_paths(paths: List[str], config: LintConfig) -> Optional[List[str]]:
    """Intersect the requested paths with files differing from HEAD.
    None → git failed (caller reports usage error); [] → nothing to lint."""
    start = config.root or os.getcwd()
    try:
        # git prints paths relative to the TOPLEVEL, not the cwd or the
        # config root — resolve against it or every join misses
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=start, capture_output=True, text=True, timeout=30)
        if top.returncode != 0 or not top.stdout.strip():
            return None
        root = top.stdout.strip()
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30)
        # diff-vs-HEAD omits brand-new files — the MOST common pre-commit
        # case; untracked (non-ignored) files count as changed too
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0 or untracked.returncode != 0:
        return None
    changed = [os.path.join(root, ln.strip())
               for ln in (out.stdout.splitlines()
                          + untracked.stdout.splitlines())
               if ln.strip().endswith(".py")]
    wanted = [os.path.abspath(p) for p in paths]
    keep = []
    for c in changed:
        ac = os.path.abspath(c)
        if not os.path.isfile(ac):
            continue  # deleted in the working tree
        if any(ac == w or ac.startswith(w + os.sep) for w in wanted):
            keep.append(ac)
    return keep


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()
    if args.check and not args.fix:
        print("dtxlint: --check requires --fix", file=sys.stderr)
        return 2

    if args.no_config:
        config = LintConfig()
    else:
        config = load_config(start=args.paths[0] if args.paths else ".")
    if args.no_cache:
        config = dataclasses.replace(config, cache="")
    if args.no_program:
        config = dataclasses.replace(config, program=False)
    if args.select:
        wanted = [r.strip() for r in args.select.split(",") if r.strip()]
        known = {cls.id for cls in RULE_CLASSES}
        unknown = sorted(set(wanted) - known)
        if unknown:
            # a typo must not turn the gate green by selecting zero rules
            print(f"dtxlint: unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        rules = rules_by_id(wanted)
    else:
        rules = all_rules()

    paths = list(args.paths)
    if args.changed:
        changed = _changed_paths(paths, config)
        if changed is None:
            print("dtxlint: --changed requires a git checkout with a HEAD "
                  "commit", file=sys.stderr)
            return 2
        if not changed:
            if args.format == "json":
                # the documented stdout contract holds on every exit path
                print(json.dumps({"version": JSON_SCHEMA_VERSION,
                                  "findings": [], "baselined": 0,
                                  "suppressed": 0, "files": 0,
                                  "failed": False}, indent=1))
            else:
                print("dtxlint: no changed python files under the given "
                      "paths")
            return 0
        paths = changed

    fix_summary = None
    if args.fix:
        from datatunerx_tpu.analysis.fix import FIXABLE_RULES, fix_paths

        fixable = [r.id for r in rules if r.id in FIXABLE_RULES]
        outcomes = fix_paths(paths, config=config, rule_ids=fixable,
                             write=not args.check)
        changed_files = [o for o in outcomes if o.changed]
        fix_summary = {
            "fixed": sum(o.applied for o in changed_files),
            "files_changed": len(changed_files),
            "unfixable": sum(o.unfixable for o in outcomes),
        }
        if args.check:
            if args.format == "json":
                print(json.dumps({"version": JSON_SCHEMA_VERSION,
                                  "fix": fix_summary,
                                  "would_change": [o.path
                                                   for o in changed_files],
                                  "failed": bool(changed_files)}, indent=1))
            elif changed_files:
                for o in changed_files:
                    print(f"{o.path}: {o.applied} fix(es) would be applied "
                          "— run `dtxlint --fix`")
            else:
                print("dtxlint: --fix --check clean (no applicable fixes)")
            return 1 if changed_files else 0

    stats = None
    if config.program:
        from datatunerx_tpu.analysis.program import lint_program

        result, stats = lint_program(paths, config=config, rules=rules)
    else:
        result = lint_paths(paths, config=config, rules=rules)

    baseline_path = args.baseline or config.resolve(config.baseline)
    if args.write_baseline:
        baseline_mod.save_baseline(baseline_path, result.findings)
        print(f"dtxlint: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    carried = (baseline_mod.load_baseline(baseline_path)
               if not args.no_baseline else baseline_mod.load_baseline(""))
    new, baselined = baseline_mod.partition(result.findings, carried)
    gate = [f for f in new
            if _SEVERITY_RANK.get(f.severity, 1)
            >= _SEVERITY_RANK[args.fail_on]]

    if args.format == "json":
        doc = {
            "version": JSON_SCHEMA_VERSION,
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "suppressed": result.suppressed,
            "files": result.files,
            "failed": bool(gate),
        }
        if stats is not None:
            doc["cache"] = {"analyzed": stats.analyzed,
                            "reused": stats.reused}
        if fix_summary is not None:
            doc["fix"] = fix_summary
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print(f.render())
        summary = (f"dtxlint: {len(new)} finding(s) in {result.files} "
                   f"file(s)")
        extras = []
        if result.suppressed:
            extras.append(f"{result.suppressed} suppressed inline")
        if baselined:
            extras.append(f"{len(baselined)} baselined")
        if stats is not None and stats.reused:
            extras.append(f"{stats.reused} module(s) from cache")
        if fix_summary is not None:
            extras.append(f"{fix_summary['fixed']} auto-fixed")
        if extras:
            summary += " (" + ", ".join(extras) + ")"
        print(summary)
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
