"""Lightweight intra-module call graph for dtxlint rules.

Whole-program analysis is out of scope (and overkill for the bug classes
we chase); what the rules need is "which functions in THIS module are
reachable from a hot root" (DTX001) and "which methods of THIS class run
on a background thread" (DTX006). Both come from one pass:

  * every def/async def gets a qualname — ``f`` at module level,
    ``C.m`` for methods, ``outer.<locals>.inner`` for nested defs;
  * call edges: bare-name calls to module-level functions, and
    ``self.m()`` / ``cls.m()`` calls to sibling methods;
  * reference edges: a function passed as a call ARGUMENT (``jax.jit(f)``,
    ``Thread(target=self._worker)``) — the callee will run it, so
    reachability must flow through;
  * nesting edges: an enclosing function reaches its nested defs (the
    closure is defined there; if it escapes uncalled we over-approximate,
    which for a linter is the safe direction).

Import aliases (``import jax.numpy as jnp``, ``from jax import random``)
are resolved so rules can match on canonical dotted names like
``jax.numpy.asarray`` regardless of local spelling.
"""

from __future__ import annotations

import ast
import fnmatch
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def collect_aliases(tree: ast.Module, module: Optional[str] = None,
                    is_package: bool = False) -> Dict[str, str]:
    """Local name → canonical dotted prefix for every import in the module.

    ``module``/``is_package`` give the importing module's own dotted name so
    RELATIVE imports resolve to canonical names too: inside
    ``datatunerx_tpu.gateway.server``, ``from ..utils.storage import open_uri``
    maps ``open_uri`` → ``datatunerx_tpu.utils.storage.open_uri``. Without
    module context (fixtures, stdin) relative imports are skipped as before.
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    aliases[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            elif module:
                parts = module.split(".")
                if not is_package:
                    parts = parts[:-1]
                if node.level - 1 > len(parts):
                    continue
                parts = parts[:len(parts) - (node.level - 1)]
                base = ".".join(parts + ([node.module] if node.module else []))
            else:
                continue
            if not base:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{base}.{a.name}"
    return aliases


def resolve_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name for a Name/Attribute chain, through aliases.
    ``jnp.asarray`` → ``jax.numpy.asarray``; non-name expressions → None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(aliases.get(cur.id, cur.id))
    return ".".join(reversed(parts))


def walk_function(fn: ast.AST, include_nested: bool = False) -> Iterator[ast.AST]:
    """Yield the nodes of one function's own body, optionally descending
    into nested def/class bodies (default: stop at them — nested defs are
    separate call-graph nodes)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not include_nested and isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class FunctionInfo:
    qualname: str
    name: str
    node: ast.AST
    cls: Optional[str] = None  # owning class name for methods
    lineno: int = 0


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)


class ModuleGraph:
    def __init__(self, tree: ast.Module, aliases: Optional[Dict[str, str]] = None):
        self.aliases = aliases if aliases is not None else collect_aliases(tree)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.edges: Dict[str, Set[str]] = {}
        # per-caller call/reference sites with line numbers: local targets
        # (qualnames) and external dotted names (through import aliases) —
        # the raw material for hot-region roots and the program graph
        self.edge_sites: Dict[str, List[Tuple[str, int]]] = {}
        self.external_sites: Dict[str, List[Tuple[str, int]]] = {}
        # CALL-only subsets (no reference/nesting edges): a function handed
        # to Thread(target=...) or map() runs on another frame — DTX009's
        # held-lock reachability must not follow it, while DTX001 hot-path
        # reachability deliberately does
        self.call_edges: Dict[str, Set[str]] = {}
        self.external_calls: Dict[str, List[Tuple[str, int]]] = {}
        # calls executed at import time (module/class bodies, not functions)
        self.module_sites: List[Tuple[str, int]] = []
        self.module_external_sites: List[Tuple[str, int]] = []
        self._module_level: Dict[str, str] = {}  # bare name → qualname
        self._collect(tree.body, prefix="", cls=None)
        for qualname, info in self.functions.items():
            self.edges[qualname] = self._edges_from(qualname, info)
        self._collect_module_sites(tree)

    # ------------------------------------------------------------ building
    def _collect(self, body, prefix: str, cls: Optional[str]):
        for node in body:
            if isinstance(node, _FUNC_NODES):
                qual = f"{prefix}{node.name}"
                info = FunctionInfo(qual, node.name, node, cls=cls,
                                    lineno=node.lineno)
                self.functions[qual] = info
                if cls is not None and prefix == f"{cls}.":
                    self.classes[cls].methods[node.name] = info
                if prefix == "":
                    self._module_level[node.name] = qual
                self._collect(node.body, prefix=f"{qual}.<locals>.", cls=cls)
            elif isinstance(node, ast.ClassDef) and prefix == "":
                self.classes[node.name] = ClassInfo(node.name, node)
                self._collect(node.body, prefix=f"{node.name}.", cls=node.name)

    def _target_of(self, expr: ast.AST, info: FunctionInfo) -> Optional[str]:
        """Qualname a Name/Attribute expression refers to, if it names a
        function in this module."""
        if isinstance(expr, ast.Name):
            local = f"{info.qualname}.<locals>.{expr.id}"
            if local in self.functions:
                return local
            return self._module_level.get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in ("self", "cls") and info.cls):
            sibling = f"{info.cls}.{expr.attr}"
            if sibling in self.functions:
                return sibling
        return None

    def _edges_from(self, qualname: str, info: FunctionInfo) -> Set[str]:
        out: Set[str] = set()
        sites = self.edge_sites.setdefault(qualname, [])
        ext = self.external_sites.setdefault(qualname, [])
        calls = self.call_edges.setdefault(qualname, set())
        ext_calls = self.external_calls.setdefault(qualname, [])
        # nesting edges
        nested_prefix = f"{qualname}.<locals>."
        for other in self.functions:
            if other.startswith(nested_prefix) and "." not in other[len(nested_prefix):]:
                out.add(other)
                sites.append((other, info.lineno))
        for node in walk_function(info.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self._target_of(node.func, info)
            if callee:
                out.add(callee)
                sites.append((callee, node.lineno))
                calls.add(callee)
            else:
                dotted = resolve_name(node.func, self.aliases)
                if dotted:
                    ext.append((dotted, node.lineno))
                    ext_calls.append((dotted, node.lineno))
            # reference edges: functions handed to another callable
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                ref = self._target_of(arg, info)
                if ref:
                    out.add(ref)
                    sites.append((ref, node.lineno))
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    dotted = resolve_name(arg, self.aliases)
                    if dotted:
                        ext.append((dotted, node.lineno))
        return out

    def _collect_module_sites(self, tree: ast.Module):
        """Call sites at import time: module body and class bodies, stopping
        at function boundaries (their bodies run when called)."""
        stack: List[ast.AST] = list(tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) \
                    and node.func.id in self._module_level:
                self.module_sites.append(
                    (self._module_level[node.func.id], node.lineno))
            else:
                dotted = resolve_name(node.func, self.aliases)
                if dotted:
                    self.module_external_sites.append((dotted, node.lineno))

    # ------------------------------------------------------------- queries
    def call_target(self, expr: ast.AST, caller: str) -> Optional[str]:
        """Qualname a call's func expression refers to, when it names a
        function in this module and ``caller`` is the enclosing function's
        qualname (public form of the edge-building resolution, used by the
        program-pass summary builder)."""
        info = self.functions.get(caller)
        if info is None:
            return None
        return self._target_of(expr, info)

    def reachable(self, patterns: Tuple[str, ...]) -> Set[str]:
        """Every function reachable (inclusive) from functions whose BARE
        name matches one of the fnmatch patterns."""
        roots = [q for q, i in self.functions.items()
                 if any(fnmatch.fnmatchcase(i.name, p) for p in patterns)]
        return self.reachable_from(roots)

    def reachable_from(self, roots) -> Set[str]:
        """Every function reachable (inclusive) from the given qualnames."""
        seen: Set[str] = set()
        stack = [q for q in roots if q in self.functions]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.edges.get(cur, ()))
        return seen

    def class_reachable(self, cls: str, method_names: Set[str]) -> Set[str]:
        """Methods of ``cls`` reachable (inclusive) from the named methods,
        following only intra-class edges."""
        seen: Set[str] = set()
        stack = [f"{cls}.{m}" for m in method_names if f"{cls}.{m}" in self.functions]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in self.edges.get(cur, ()):
                fi = self.functions.get(nxt)
                if fi is not None and fi.cls == cls:
                    stack.append(nxt)
        return seen

    def thread_entry_methods(self, cls: str) -> Set[str]:
        """Bare names of ``cls`` methods used as a Thread/Timer ``target=``
        anywhere in the class body."""
        entries: Set[str] = set()
        cinfo = self.classes.get(cls)
        if cinfo is None:
            return entries
        for node in ast.walk(cinfo.node):
            if not isinstance(node, ast.Call):
                continue
            callee = resolve_name(node.func, self.aliases)
            if callee not in ("threading.Thread", "threading.Timer"):
                continue
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    entries.add(kw.value.attr)
        return entries
