"""``dtxlint --fix``: AST-anchored span edits for the mechanical rules.

Scope is deliberately narrow — a fix is only offered when it is
*provably* behavior-preserving at the AST level, and every applied fix
is validated by re-parsing and re-linting the result (a fix that does
not strictly reduce the fixable-finding count is rolled back and the
file left untouched):

  * DTX002 hoist-jit-out-of-loop — ``name = jax.jit(...)`` directly in a
    loop body is hoisted above the (outermost enclosing) loop, but ONLY
    when the right-hand side reads no name assigned anywhere in those
    loops (``for f in fns: g = jax.jit(f)`` is NOT hoistable — ``f``
    varies — and is reported as unfixable instead of mangled).
  * DTX008 wrap-import-time-device-work — a device-allocating function
    DEFAULT (``def f(x, fill=jnp.zeros((4,))):``) becomes ``fill=None``
    plus an ``if fill is None: fill = jnp.zeros((4,))`` materialization
    at the top of the body: the classic default-argument deferral.
    Module-level constants (``TABLE = jnp.ones(...)``) have no
    call-site-compatible mechanical rewrite and stay manual.
  * DTX004 prng-key-split insertion — the canonical recipe for key
    reuse: ``key, key_split1 = jax.random.split(key)`` is inserted
    before the anchor consumption and the anchor call is rewritten to
    consume the fresh subkey. For a straight double-consumption the
    anchor is the FIRST consuming statement (splitting after it would
    itself reuse the key); for a key consumed inside a loop but
    assigned outside, the anchor is the flagged statement in the loop
    body — the inserted split rebinds the carry each iteration. This
    fixer deliberately CHANGES runtime values: that is the point (the
    flagged code draws correlated randomness; the fix decorrelates it),
    so unlike DTX002/DTX008 it is value-changing-by-design.

The edit engine is a flat list of non-overlapping ``SpanEdit``s in
character offsets; ``apply_edits`` refuses (raises ``OverlapError``)
rather than guessing when two edits touch the same span.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.config import LintConfig, rule_enabled
from datatunerx_tpu.analysis.core import (
    Finding,
    ModuleContext,
    filter_findings,
    module_name_for_path,
    suppressions,
)

FIXABLE_RULES = ("DTX002", "DTX004", "DTX008")
_MAX_PASSES = 8
_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


class OverlapError(ValueError):
    """Two edits touch the same span — refuse rather than guess."""


@dataclass(frozen=True)
class SpanEdit:
    start: int
    end: int
    text: str


def apply_edits(source: str, edits: Sequence[SpanEdit]) -> str:
    """Apply non-overlapping edits (insertions are zero-width spans).
    Adjacent edits are fine; overlapping ones raise OverlapError."""
    out: List[str] = []
    pos = 0
    for e in sorted(edits, key=lambda e: (e.start, e.end)):
        if e.end < e.start or e.start < 0 or e.end > len(source):
            raise OverlapError(f"edit span ({e.start}, {e.end}) out of range")
        if e.start < pos:
            raise OverlapError(
                f"edit at {e.start} overlaps a previous edit ending at {pos}")
        out.append(source[pos:e.start])
        out.append(e.text)
        pos = e.end
    out.append(source[pos:])
    return "".join(out)


def _line_offsets(source: str) -> List[int]:
    """offsets[i] = char offset where 1-based line i starts (offsets[0]
    unused); one trailing sentinel for end-of-source."""
    offsets = [0, 0]
    for i, ch in enumerate(source):
        if ch == "\n":
            offsets.append(i + 1)
    offsets.append(len(source))
    return offsets


def _node_span(offsets: List[int], node: ast.AST) -> Tuple[int, int]:
    start = offsets[node.lineno] + node.col_offset
    end = offsets[node.end_lineno] + node.end_col_offset
    return start, end


def _line_start(offsets: List[int], line: int) -> int:
    return offsets[min(line, len(offsets) - 1)]


def _find_call(ctx: ModuleContext, finding: Finding) -> Optional[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and node.lineno == finding.line \
                and node.col_offset == finding.col:
            return node
    return None


# ------------------------------------------------------------ DTX002 hoist

def _stores_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                          ast.Del)):
            out.add(n.id)
    return out


def _loads_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _fix_dtx002(ctx: ModuleContext, finding: Finding,
                offsets: List[int]) -> Optional[List[SpanEdit]]:
    call = _find_call(ctx, finding)
    if call is None or ctx.resolve(call.func) not in _JIT_NAMES:
        return None  # the static_argnums sub-finding anchors on the kwarg
    stmt = ctx.parents.get(call)
    if not (isinstance(stmt, ast.Assign) and stmt.value is call
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)):
        return None
    # the statement must sit DIRECTLY in a loop body; collect the chain of
    # enclosing loops up to the function/module boundary
    loops: List[ast.AST] = []
    cur: Optional[ast.AST] = stmt
    while cur is not None:
        parent = ctx.parents.get(cur)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda, ast.ClassDef)) or parent is None:
            break
        if isinstance(parent, _LOOPS):
            loops.append(parent)
        cur = parent
    if not loops or stmt not in loops[0].body:
        return None
    mutated: Set[str] = set()
    for loop in loops:
        mutated |= _stores_in(loop)
    if _loads_in(stmt.value) & mutated:
        return None  # rhs depends on loop state: hoisting changes behavior
    outer = loops[-1]
    # whole-line statement only (no `a = 1; b = jax.jit(f)` splicing)
    line = ctx.lines[stmt.lineno - 1]
    if line[:stmt.col_offset].strip():
        return None
    tail = ctx.lines[stmt.end_lineno - 1][stmt.end_col_offset:].strip()
    if tail and not tail.startswith("#"):
        return None
    dedent = stmt.col_offset - outer.col_offset
    moved_lines = []
    for ln in range(stmt.lineno, stmt.end_lineno + 1):
        text = ctx.lines[ln - 1]
        moved_lines.append(text[dedent:] if text[:dedent].strip() == ""
                           else text)
    moved = "\n".join(moved_lines) + "\n"
    del_start = _line_start(offsets, stmt.lineno)
    del_end = _line_start(offsets, stmt.end_lineno + 1)
    ins_at = _line_start(offsets, outer.lineno)
    return [SpanEdit(ins_at, ins_at, moved),
            SpanEdit(del_start, del_end, "")]


# --------------------------------------------------- DTX008 default-arg fix

def _default_param(fn: ast.AST, node: ast.AST) -> Optional[str]:
    """Param name when ``node`` is exactly one of ``fn``'s default-value
    expressions."""
    a = fn.args
    pos_params = [p.arg for p in a.posonlyargs + a.args]
    for i, default in enumerate(a.defaults):
        if default is node:
            return pos_params[len(pos_params) - len(a.defaults) + i]
    for i, default in enumerate(a.kw_defaults):
        if default is node:
            return a.kwonlyargs[i].arg
    return None


def _fix_dtx008(ctx: ModuleContext, finding: Finding,
                offsets: List[int]) -> Optional[List[SpanEdit]]:
    call = _find_call(ctx, finding)
    if call is None:
        return None
    fn = ctx.parents.get(call)
    while fn is not None and not isinstance(fn, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)):
        fn = ctx.parents.get(fn)
    if fn is None:
        return None  # module/class-level device work: no mechanical rewrite
    pname = _default_param(fn, call)
    if pname is None:
        return None  # flagged call is a SUBEXPRESSION of the default
    if call.lineno != call.end_lineno:
        return None  # multiline default: keep the fix mechanical
    expr = ast.get_source_segment(ctx.source, call)
    if expr is None:
        return None
    body = fn.body
    has_doc = (isinstance(body[0], ast.Expr)
               and isinstance(body[0].value, ast.Constant)
               and isinstance(body[0].value.value, str))
    if has_doc and len(body) > 1:
        insert_before = body[1]  # keep the docstring first
        indent = " " * insert_before.col_offset
        ins_at = _line_start(offsets, insert_before.lineno)
    elif has_doc:
        # docstring-only body: insert AFTER it (inserting before would
        # demote it to a bare string and destroy __doc__)
        indent = " " * body[0].col_offset
        ins_at = _line_start(offsets, body[0].end_lineno + 1)
    else:
        insert_before = body[0]
        indent = " " * insert_before.col_offset
        ins_at = _line_start(offsets, insert_before.lineno)
    guard = (f"{indent}if {pname} is None:\n"
             f"{indent}    {pname} = {expr}\n")
    start, end = _node_span(offsets, call)
    return [SpanEdit(start, end, "None"), SpanEdit(ins_at, ins_at, guard)]


# ----------------------------------------------- DTX004 key-split insertion

_PRIOR_LINE_RE = re.compile(r"already consumed at line (\d+)")


def _key_arg_node(call: ast.Call) -> Optional[ast.Name]:
    """The Name node the call consumes as its PRNG key (first positional
    arg or ``key=``) — the same extraction DTX004's rule does."""
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value
    return None


def _enclosing_stmt(ctx: ModuleContext, node: ast.AST) -> Optional[ast.stmt]:
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = ctx.parents.get(cur)
    return cur if isinstance(cur, ast.stmt) else None


def _whole_line_stmt(ctx: ModuleContext, stmt: ast.stmt) -> bool:
    """True when the statement owns its line(s): nothing before it on its
    first line, nothing but a comment after it on its last (the same guard
    DTX002's hoist applies — no `a = 1; b = f(k)` splicing)."""
    line = ctx.lines[stmt.lineno - 1]
    if line[:stmt.col_offset].strip():
        return False
    tail = ctx.lines[stmt.end_lineno - 1][stmt.end_col_offset:].strip()
    return not tail or tail.startswith("#")


def _fresh_name(ctx: ModuleContext, base: str) -> str:
    used = {n.id for n in ast.walk(ctx.tree) if isinstance(n, ast.Name)}
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn.args
            used.update(p.arg for p in a.posonlyargs + a.args + a.kwonlyargs)
    i = 1
    while f"{base}_split{i}" in used:
        i += 1
    return f"{base}_split{i}"


def _first_consumer_at(ctx: ModuleContext, line: int,
                       name: str) -> Optional[ast.Call]:
    """Earliest jax.random call on ``line`` consuming ``name`` as its key
    (the prior consumption the finding message points at)."""
    best: Optional[ast.Call] = None
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and node.lineno == line):
            continue
        resolved = ctx.resolve(node.func)
        if not resolved or not resolved.startswith("jax.random."):
            continue
        key = _key_arg_node(node)
        if key is None or key.id != name:
            continue
        if best is None or node.col_offset < best.col_offset:
            best = node
    return best


def _fix_dtx004(ctx: ModuleContext, finding: Finding,
                offsets: List[int]) -> Optional[List[SpanEdit]]:
    flagged = _find_call(ctx, finding)
    if flagged is None:
        return None
    key = _key_arg_node(flagged)
    if key is None:
        return None
    # the split expression reuses the flagged call's own module path
    # (`jax.random.normal(k)` → `jax.random.split`), so the insertion can
    # never reference a name the module didn't import; a bare imported
    # name (`from jax.random import normal`) has no such path → manual
    if not isinstance(flagged.func, ast.Attribute):
        return None
    base_src = ast.get_source_segment(ctx.source, flagged.func.value)
    if base_src is None or "\n" in base_src:
        return None
    m = _PRIOR_LINE_RE.search(finding.message)
    if m:
        # double consumption: anchor at the FIRST consuming statement —
        # splitting before it rebinds the key, so the flagged (later)
        # consumption draws from the new carry, not the consumed value
        anchor_call = _first_consumer_at(ctx, int(m.group(1)), key.id)
        if anchor_call is None:
            return None
    else:
        # loop-reuse: anchor at the flagged statement inside the loop —
        # the inserted split rebinds the carry every iteration
        anchor_call = flagged
    stmt = _enclosing_stmt(ctx, anchor_call)
    if stmt is None or not _whole_line_stmt(ctx, stmt):
        return None
    target = _key_arg_node(anchor_call)
    if target is None or target.id != key.id:
        return None
    fresh = _fresh_name(ctx, key.id)
    indent = " " * stmt.col_offset
    ins = (f"{indent}{key.id}, {fresh} = {base_src}.split({key.id})\n")
    ins_at = _line_start(offsets, stmt.lineno)
    kstart, kend = _node_span(offsets, target)
    return [SpanEdit(ins_at, ins_at, ins), SpanEdit(kstart, kend, fresh)]


_FIXERS = {"DTX002": _fix_dtx002, "DTX004": _fix_dtx004,
           "DTX008": _fix_dtx008}


def _overlaps(group: Sequence[SpanEdit],
              spans: Sequence[Tuple[int, int]]) -> bool:
    """True when any edit in ``group`` intersects an already-chosen span.
    Zero-width insertions never overlap anything (apply_edits orders
    same-offset insertions stably)."""
    for ge in group:
        for s, e in spans:
            if max(ge.start, s) < min(ge.end, e):
                return True
    return False


# ---------------------------------------------------------------- driver

@dataclass
class FixResult:
    path: str
    applied: int = 0
    unfixable: int = 0
    changed: bool = False


def _fixable_findings(source: str, path: str, config: LintConfig,
                      rule_ids: Sequence[str]) -> Tuple[List[Finding],
                                                        Optional[ModuleContext]]:
    from datatunerx_tpu.analysis.rules import rules_by_id

    module, is_package = module_name_for_path(path)
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return [], None
    ctx = ModuleContext(path, source, tree, config, module=module,
                        is_package=is_package)
    raw: List[Finding] = []
    for rule in rules_by_id(list(rule_ids)):
        if rule_enabled(config, rule.id):
            raw.extend(rule.check(ctx))
    findings, _ = filter_findings(raw, suppressions(source), config)
    return findings, ctx


def fix_source(source: str, path: str,
               config: Optional[LintConfig] = None,
               rule_ids: Sequence[str] = FIXABLE_RULES,
               ) -> Tuple[str, FixResult]:
    """Iteratively apply safe fixes to one module's source. Each pass
    re-parses and re-lints; a pass that fails to strictly reduce the
    fixable-finding count is rolled back."""
    config = config or LintConfig()
    rule_ids = [r for r in rule_ids if r in _FIXERS]
    res = FixResult(path=path)
    for _ in range(_MAX_PASSES):
        findings, ctx = _fixable_findings(source, path, config, rule_ids)
        if ctx is None or not findings:
            res.unfixable = len(findings)
            break
        offsets = _line_offsets(source)
        chosen: List[SpanEdit] = []
        spans: List[Tuple[int, int]] = []
        for finding in findings:
            fixer = _FIXERS.get(finding.rule)
            group = fixer(ctx, finding, offsets) if fixer else None
            if not group:
                continue
            if _overlaps(group, spans):
                continue  # refused: the next pass re-derives it post-shift
            chosen.extend(group)
            spans.extend((ge.start, ge.end) for ge in group)
        if not chosen:
            res.unfixable = len(findings)
            break
        try:
            candidate = apply_edits(source, chosen)
            ast.parse(candidate)
        except (OverlapError, SyntaxError):
            res.unfixable = len(findings)
            break
        after, _ = _fixable_findings(candidate, path, config, rule_ids)
        if len(after) >= len(findings):
            res.unfixable = len(findings)
            break  # the fix didn't resolve its finding: roll back
        res.applied += len(findings) - len(after)
        source = candidate
        res.changed = True
    else:
        findings, _ = _fixable_findings(source, path, config, rule_ids)
        res.unfixable = len(findings)
    return source, res


def fix_paths(paths: Sequence[str], config: Optional[LintConfig] = None,
              rule_ids: Sequence[str] = FIXABLE_RULES,
              write: bool = True) -> List[FixResult]:
    """Run the fixer over files/trees. ``write=False`` is ``--fix
    --check``: report what WOULD change, touch nothing."""
    from datatunerx_tpu.analysis.core import _display_path, iter_python_files

    config = config or LintConfig()
    results: List[FixResult] = []
    for path in iter_python_files(paths, config):
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        fixed, res = fix_source(source, path, config=config,
                                rule_ids=rule_ids)
        res.path = _display_path(path, config)
        if res.changed and write:
            tmp = f"{path}.dtxfix.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(fixed)
            os.replace(tmp, path)
        results.append(res)
    return results
