"""Program-level analysis: the cross-module pass behind ``dtx lint``.

The per-module rules stop at the file boundary — exactly where this
repo's real bugs lived (PR 4/5 triage: drain-leak, breaker-tripping
client errors, shutdown-flag race all sat at a module seam or in
threaded gateway/engine code). This pass stitches the per-module call
graphs into ONE program graph over ``datatunerx_tpu.*`` imports
(absolute, relative, aliased, ``from x import f``, and package
re-exports through ``__init__``) and runs three cross-module checks:

  * DTX001 — hot-path reachability follows calls across files: a
    ``utils/`` helper that ``np.asarray``s is flagged when reachable
    from ``train_step`` or the engine's ``_scheduler``, with the root
    named in the message. Findings are emitted only for functions hot
    EXCLUSIVELY through cross-module edges (module-local hot paths are
    the per-module rule's job, so nothing is reported twice).
  * DTX007 — escape adjudication: a resource handle whose only use is
    "passed to an internal callee" is no longer assumed safe; the
    callee's parameter disposition (drops / disposes / escapes) decides
    whether the caller still leaks.
  * DTX009 — transitive blocking-under-lock: a call under ``with
    self._lock:`` to a function whose reachable closure contains a
    blocking site (device sync, subprocess wait, socket I/O, unbounded
    ``queue.get``) is flagged at the call site with the blocking leaf
    named.

Every analyzed module is distilled into a JSON-serializable SUMMARY
(functions, edges, sync/blocking sites, suppression lines, …) cached in
``config.cache`` keyed on file mtime+size plus a config/rule-set
fingerprint — repeat ``dtx lint`` runs skip re-parsing unchanged files
entirely and only re-run the (cheap) program pass over the summaries.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.config import (
    LintConfig,
    mesh_axes_for,
    rule_enabled,
)
from datatunerx_tpu.analysis.core import (
    Finding,
    LintResult,
    ModuleContext,
    Rule,
    _display_path,
    filter_findings,
    iter_python_files,
    module_name_for_path,
    suppressions,
)
from datatunerx_tpu.analysis.rules.blocking import (
    blocking_label,
    calls_under_lock,
)
from datatunerx_tpu.analysis.rules.concurrency import param_disposition
from datatunerx_tpu.analysis.rules.host_sync import sync_label
from datatunerx_tpu.analysis.rules.lockorder import (
    function_lock_info,
    lock_context_id,
    shortest_path,
)

CACHE_VERSION = 3  # v3: lock_acquires / lock_edges / lock_id (DTX011)

Node = Tuple[str, str]  # (abs file path, qualname)


# ----------------------------------------------------------- module summary

def _call_sites(ctx: ModuleContext, fn_node: ast.AST,
                label_fn) -> List[List]:
    """[line, col, label] for every call in one function's own body that
    ``label_fn`` labels (sync_label / blocking_label)."""
    from datatunerx_tpu.analysis.callgraph import walk_function

    out: List[List] = []
    for node in walk_function(fn_node):
        if isinstance(node, ast.Call):
            label = label_fn(ctx, node)
            if label:
                out.append([node.lineno, node.col_offset, label])
    return out


def _locked_calls(ctx: ModuleContext, qualname: str, fn_node: ast.AST,
                  seen: Set[Tuple[int, int]],
                  cls: Optional[str] = None) -> List[dict]:
    """Calls under a lock that are NOT directly blocking (those are the
    per-module DTX009's) but resolve to a local function or an imported
    dotted name — the program pass follows them through the graph (DTX009
    transitively; DTX011 via the held lock's contextualized id)."""
    out: List[dict] = []
    for call, lock in calls_under_lock(ctx, fn_node):
        key = (call.lineno, call.col_offset)
        if key in seen:
            continue
        seen.add(key)
        if blocking_label(ctx, call):
            continue
        entry = {"line": call.lineno, "col": call.col_offset, "lock": lock,
                 "lock_id": lock_context_id(ctx.module, cls, lock)}
        local = ctx.graph.call_target(call.func, qualname)
        if local:
            entry["local"] = local
        else:
            dotted = ctx.resolve(call.func)
            if not dotted:
                continue
            entry["ext"] = dotted
        out.append(entry)
    return out


def build_summary(ctx: ModuleContext) -> dict:
    """Distill one analyzed module into the JSON-serializable form the
    program pass (and the cache) consumes. Built AFTER the per-module
    rules ran, so DTX007's ``xescape_candidates`` are populated."""
    graph = ctx.graph
    funcs: Dict[str, dict] = {}
    locked_seen: Set[Tuple[int, int]] = set()
    for qualname in sorted(graph.functions):
        info = graph.functions[qualname]
        entry = {
            "name": info.name,
            "lineno": info.lineno,
            "edges": sorted(graph.edges.get(qualname, ())),
            "external": [[d, ln]
                         for d, ln in graph.external_sites.get(qualname, [])],
            # CALL-only subsets: what actually executes on this frame —
            # DTX009's held-lock reachability follows these, never the
            # reference edges (a Thread(target=...) callee runs elsewhere)
            "call_edges": sorted(graph.call_edges.get(qualname, ())),
            "external_calls": [[d, ln] for d, ln
                               in graph.external_calls.get(qualname, [])],
            "sync_sites": _call_sites(ctx, info.node, sync_label),
            "blocking_sites": _call_sites(ctx, info.node, blocking_label),
            "locked_calls": _locked_calls(ctx, qualname, info.node,
                                          locked_seen, cls=info.cls),
        }
        acquires, lock_edges = function_lock_info(ctx, info)
        entry["lock_acquires"] = acquires
        entry["lock_edges"] = lock_edges
        if "." not in qualname:  # module-level fn: DTX007 adjudication data
            a = info.node.args
            entry["params"] = [p.arg for p in a.posonlyargs + a.args]
            entry["dispositions"] = {
                p.arg: param_disposition(ctx, info.node, p.arg)
                for p in a.posonlyargs + a.args + a.kwonlyargs}
        funcs[qualname] = entry
    return {
        "module": ctx.module,
        "functions": funcs,
        "classes": {c: "__init__" in graph.classes[c].methods
                    for c in graph.classes},
        "aliases": dict(ctx.aliases),
        "hot_regions": [list(r) for r in ctx.hot_regions],
        "edge_sites": {q: [[t, ln] for t, ln in s]
                       for q, s in graph.edge_sites.items() if s},
        "module_sites": [[t, ln] for t, ln in graph.module_sites],
        "suppressions": {str(ln): sorted(ids)
                         for ln, ids in suppressions(ctx.source).items()},
        "xescape": list(ctx.xescape_candidates),
    }


def _empty_summary(module: Optional[str] = None) -> dict:
    return {"module": module, "functions": {}, "classes": {}, "aliases": {},
            "hot_regions": [], "edge_sites": {}, "module_sites": [],
            "suppressions": {}, "xescape": []}


# ------------------------------------------------------------ program graph

class ProgramGraph:
    """Cross-module call graph over module summaries. ``records`` maps the
    abs file path to {"display", "summary", "findings", "suppressed"}."""

    def __init__(self, records: Dict[str, dict]):
        self.records = records
        self.mod_by_name: Dict[str, str] = {}
        self.func_map: Dict[str, Node] = {}
        for path, rec in records.items():
            s = rec["summary"]
            m = s.get("module")
            if not m:
                continue
            self.mod_by_name[m] = path
            for q in s["functions"]:
                self.func_map[f"{m}.{q}"] = (path, q)
            for cname, has_init in s["classes"].items():
                if has_init:
                    # instantiation runs __init__: SomeClass() edges there
                    self.func_map.setdefault(
                        f"{m}.{cname}", (path, f"{cname}.__init__"))
        self._edges_memo: Dict[Tuple[Node, str], List[Node]] = {}

    def resolve(self, dotted: str, depth: int = 0) -> Optional[Node]:
        """Dotted call name → program node, following package re-exports
        (``from datatunerx_tpu.utils import open_uri`` where ``utils/
        __init__`` re-exports it from ``storage``) a bounded number of
        hops."""
        if not dotted or depth > 8:
            return None
        hit = self.func_map.get(dotted)
        if hit is not None:
            return hit
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            path = self.mod_by_name.get(mod)
            if path is None:
                continue
            aliases = self.records[path]["summary"]["aliases"]
            head = parts[i]
            if head in aliases:
                renamed = ".".join([aliases[head]] + parts[i + 1:])
                if renamed != dotted:
                    return self.resolve(renamed, depth + 1)
            return None
        return None

    def edges_of(self, node: Node) -> List[Node]:
        return self._edges(node, "edges", "external")

    def call_edges_of(self, node: Node) -> List[Node]:
        """Only edges that execute on the caller's frame (no reference /
        nesting edges) — what DTX009's held-lock reachability follows."""
        return self._edges(node, "call_edges", "external_calls")

    def _edges(self, node: Node, local_key: str, ext_key: str) -> List[Node]:
        memo_key = (node, local_key)
        memo = self._edges_memo.get(memo_key)
        if memo is not None:
            return memo
        path, q = node
        s = self.records[path]["summary"]
        f = s["functions"].get(q)
        out: List[Node] = []
        if f is not None:
            out = [(path, t) for t in f[local_key] if t in s["functions"]]
            for dotted, _ln in f[ext_key]:
                hit = self.resolve(dotted)
                if hit is not None:
                    out.append(hit)
        self._edges_memo[memo_key] = out
        return out


def _module_hot_roots(summary: dict, config: LintConfig) -> Set[str]:
    """Summary-form mirror of rules.host_sync.hot_roots: hot-pattern
    functions, functions defined in a hot region, and call targets of
    hot-region call sites."""
    funcs = summary["functions"]
    pats = tuple(config.hot_functions)
    roots = {q for q, f in funcs.items()
             if any(fnmatch.fnmatchcase(f["name"], p) for p in pats)}
    regions = [tuple(r) for r in summary["hot_regions"]]
    if regions:
        def in_region(line: int) -> bool:
            return any(s <= line <= e for s, e in regions)

        for q, f in funcs.items():
            if in_region(f["lineno"]):
                roots.add(q)
        for _q, sites in summary["edge_sites"].items():
            for target, ln in sites:
                if in_region(ln):
                    roots.add(target)
        for target, ln in summary["module_sites"]:
            if in_region(ln):
                roots.add(target)
    return roots


def _intra_reachable(summary: dict, roots: Set[str]) -> Set[str]:
    funcs = summary["functions"]
    seen: Set[str] = set()
    stack = [q for q in roots if q in funcs]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(t for t in funcs[cur]["edges"] if t in funcs)
    return seen


# ---------------------------------------------------------- program passes

def _program_dtx001(prog: ProgramGraph, config: LintConfig) -> List[Finding]:
    """Sync sites in functions hot ONLY through cross-module reachability
    (module-local hot paths are already the per-module rule's)."""
    local_hot: Dict[str, Set[str]] = {}
    stack: List[Tuple[Node, Node]] = []
    for path, rec in prog.records.items():
        s = rec["summary"]
        roots = _module_hot_roots(s, config)
        local_hot[path] = _intra_reachable(s, roots)
        stack.extend(((path, q), (path, q))
                     for q in roots if q in s["functions"])
    origin: Dict[Node, Node] = {}
    while stack:
        node, root = stack.pop()
        if node in origin:
            continue
        origin[node] = root
        stack.extend((n, root) for n in prog.edges_of(node))
    out: List[Finding] = []
    for node in sorted(origin):
        path, q = node
        if q in local_hot.get(path, ()):
            continue
        rec = prog.records[path]
        f = rec["summary"]["functions"].get(q)
        if f is None:
            continue
        rpath, rq = origin[node]
        root_desc = f"{prog.records[rpath]['display']}::{rq}"
        for ln, col, label in f["sync_sites"]:
            out.append(Finding(
                "DTX001", rec["display"], ln, col,
                f"{label} in hot path ({q} is reachable from {root_desc} "
                "via the program call graph); this blocks the host on the "
                "device stream every step — move it behind a logging "
                "boundary or use MetricsBuffer"))
    return out


def _param_for(f: dict, arg) -> Optional[str]:
    if isinstance(arg, int):
        params = f.get("params", [])
        return params[arg] if 0 <= arg < len(params) else None
    return arg if arg in f.get("dispositions", {}) else None


def _program_dtx007(prog: ProgramGraph) -> List[Finding]:
    """Adjudicate handle-passed-to-internal-callee candidates: if EVERY
    target is an internal function that merely drops the parameter, the
    caller still leaks the handle."""
    out: List[Finding] = []
    for path in sorted(prog.records):
        rec = prog.records[path]
        s = rec["summary"]
        for cand in s["xescape"]:
            if not cand["targets"]:
                continue
            callee_desc = None
            all_drop = True
            for t in cand["targets"]:
                callee = t["callee"]
                if "." not in callee:
                    node = (path, callee) if callee in s["functions"] \
                        else None
                else:
                    node = prog.resolve(callee)
                f = (prog.records[node[0]]["summary"]["functions"]
                     .get(node[1]) if node is not None else None)
                pname = _param_for(f, t["arg"]) if f is not None else None
                if pname is None \
                        or f["dispositions"].get(pname, "escapes") != "drops":
                    all_drop = False  # unknown/external/disposing: escape
                    break
                callee_desc = callee
            if all_drop:
                out.append(Finding(
                    "DTX007", rec["display"], cand["line"], cand["col"],
                    f"{cand['kind']} handle `{cand['var']}` is only passed "
                    f"to {callee_desc}(), which neither closes, stores, nor "
                    "hands it on (program-graph escape analysis) — the "
                    "handle still leaks when the caller returns"))
    return out


def _program_dtx009(prog: ProgramGraph) -> List[Finding]:
    """Locked calls whose callee's reachable closure contains a blocking
    site: flagged at the call site, with the blocking leaf named."""
    direct: Dict[Node, Tuple[str, int]] = {}
    for path, rec in prog.records.items():
        for q, f in rec["summary"]["functions"].items():
            if f["blocking_sites"]:
                ln, _col, label = f["blocking_sites"][0]
                direct[(path, q)] = (label, ln)
    memo: Dict[Node, Optional[Node]] = {}

    def reach_blocker(start: Node) -> Optional[Node]:
        if start in memo:
            return memo[start]
        seen: Set[Node] = set()
        stack = [start]
        hit: Optional[Node] = None
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if cur in direct:
                hit = cur
                break
            stack.extend(prog.call_edges_of(cur))
        memo[start] = hit
        return hit

    out: List[Finding] = []
    for path in sorted(prog.records):
        rec = prog.records[path]
        s = rec["summary"]
        for q in sorted(s["functions"]):
            for lc in s["functions"][q]["locked_calls"]:
                if "local" in lc:
                    target: Optional[Node] = (path, lc["local"])
                    if lc["local"] not in s["functions"]:
                        target = None
                    name = lc["local"]
                else:
                    target = prog.resolve(lc["ext"])
                    name = lc["ext"]
                if target is None:
                    continue
                blocker = reach_blocker(target)
                if blocker is None:
                    continue
                label, bln = direct[blocker]
                bdisp = prog.records[blocker[0]]["display"]
                out.append(Finding(
                    "DTX009", rec["display"], lc["line"], lc["col"],
                    f"{name}() called while holding {lc['lock']} reaches "
                    f"{label} ({bdisp}:{bln}, via the program call graph) "
                    "— every thread contending on the lock convoys behind "
                    "an unbounded operation; move the call outside the "
                    "critical section or add a timeout"))
    return out


def _program_dtx011(prog: ProgramGraph) -> List[Finding]:
    """Lock-order inversions over the program graph: lexical nesting
    edges from every module, plus call-chain edges — a call made under a
    lock to a function whose reachable closure (call-only edges, DTX009's
    reachability) acquires another lock. Cycles are potential ABBA
    deadlocks; cycles provable from ONE module's lexical edges alone are
    the per-module DTX011's and are skipped here."""
    # lock-id edge → evidence {display, line, col, kind, mod, desc}
    edges: Dict[Tuple[str, str], dict] = {}

    def note(a: str, b: str, ev: dict):
        edges.setdefault((a, b), ev)

    # reachable lock acquisitions per node (over call-only edges)
    acq_memo: Dict[Node, Dict[str, Tuple[Node, int]]] = {}

    def reach_acquires(start: Node) -> Dict[str, Tuple[Node, int]]:
        hit = acq_memo.get(start)
        if hit is not None:
            return hit
        found: Dict[str, Tuple[Node, int]] = {}
        seen: Set[Node] = set()
        stack = [start]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            f = prog.records[cur[0]]["summary"]["functions"].get(cur[1])
            if f is None:
                continue
            for lid, ln in f.get("lock_acquires", ()):
                found.setdefault(lid, (cur, ln))
            stack.extend(prog.call_edges_of(cur))
        acq_memo[start] = found
        return found

    for path in sorted(prog.records):
        rec = prog.records[path]
        s = rec["summary"]
        for q in sorted(s["functions"]):
            f = s["functions"][q]
            for a, b, ln in f.get("lock_edges", ()):
                note(a, b, {"display": rec["display"], "line": ln,
                            "col": 0, "kind": "lex", "mod": path,
                            "desc": f"{b} acquired in {q} while holding "
                                    f"{a}"})
            for lc in f.get("locked_calls", ()):
                held = lc.get("lock_id")
                if not held:
                    continue
                if "local" in lc:
                    target: Optional[Node] = (path, lc["local"])
                    if lc["local"] not in s["functions"]:
                        target = None
                    name = lc["local"]
                else:
                    target = prog.resolve(lc["ext"])
                    name = lc["ext"]
                if target is None:
                    continue
                for lid, (leaf, lln) in sorted(reach_acquires(target)
                                               .items()):
                    if lid == held:
                        continue
                    leaf_disp = prog.records[leaf[0]]["display"]
                    note(held, lid, {
                        "display": rec["display"], "line": lc["line"],
                        "col": lc["col"], "kind": "call", "mod": path,
                        "desc": f"{name}() called in {q} while holding "
                                f"{lc['lock']} acquires {lid} at "
                                f"{leaf_disp}:{lln} (via the program "
                                "call graph)"})

    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: List[Finding] = []
    seen_cycles: Set[frozenset] = set()
    for (a, b) in sorted(edges):
        path_ids = shortest_path(graph, b, a)
        if path_ids is None:
            continue
        cycle = [a] + path_ids
        key = frozenset(cycle)
        if key in seen_cycles:
            continue
        seen_cycles.add(key)
        cycle_edges = [edges[(cycle[i], cycle[i + 1])]
                       for i in range(len(cycle) - 1)
                       if (cycle[i], cycle[i + 1]) in edges]
        if cycle_edges and all(e["kind"] == "lex" for e in cycle_edges) \
                and len({e["mod"] for e in cycle_edges}) == 1:
            continue  # single-module lexical cycle: per-module DTX011's
        ev = edges[(a, b)]
        back = edges.get((cycle[-2], a))
        back_at = (f"{back['display']}:{back['line']}" if back else "?")
        chain = " -> ".join(cycle)
        out.append(Finding(
            "DTX011", ev["display"], ev["line"], ev["col"],
            f"lock-order inversion: {ev['desc']}, but the opposite order "
            f"is taken at {back_at} (cycle {chain}) — two threads "
            "interleaving these paths deadlock; acquire in one global "
            "order",
            "error"))
    return out


# -------------------------------------------------------------- the runner

@dataclass
class ProgramStats:
    files: int = 0
    analyzed: int = 0
    reused: int = 0


def _fingerprint(config: LintConfig, rules: Sequence[Rule]) -> str:
    """Cache key half that isn't per-file: rule set + every config knob +
    the EXTRACTED mesh axes (so editing parallel/mesh.py invalidates
    cached DTX005 findings in other files)."""
    payload = {
        "v": CACHE_VERSION,
        "rules": sorted(r.id for r in rules),
        "config": {f.name: list(v) if isinstance(v, tuple) else v
                   for f in dataclasses.fields(config)
                   for v in (getattr(config, f.name),)},
        "mesh": list(mesh_axes_for(config)),
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


def _load_cache(path: str, fingerprint: str) -> dict:
    if path and os.path.isfile(path):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("fingerprint") == fingerprint:
                return doc
        except (OSError, ValueError):
            pass
    return {"fingerprint": fingerprint, "modules": {}}


def _save_cache(path: str, cache: dict):
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cache, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _analyze_file(path: str, display: str, config: LintConfig,
                  rules: Sequence[Rule]) -> dict:
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    module, is_package = module_name_for_path(path)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return {"display": display, "summary": _empty_summary(module),
                "findings": [Finding("DTX000", display, e.lineno or 0,
                                     e.offset or 0,
                                     f"syntax error: {e.msg}", "error")],
                "suppressed": 0}
    ctx = ModuleContext(display, source, tree, config, module=module,
                        is_package=is_package)
    raw: List[Finding] = []
    for rule in rules:
        if rule_enabled(config, rule.id):
            raw.extend(rule.check(ctx))
    findings, suppressed = filter_findings(raw, suppressions(source), config)
    return {"display": display, "summary": build_summary(ctx),
            "findings": findings, "suppressed": suppressed}


def _filter_program_findings(raw: List[Finding], records: Dict[str, dict],
                             config: LintConfig) -> Tuple[List[Finding], int]:
    """Program findings land on lines of files we may not have re-read
    this run — filter them against the SUMMARIES' suppression maps."""
    sup_by_display: Dict[str, Dict[int, Set[str]]] = {}
    for rec in records.values():
        sup_by_display[rec["display"]] = {
            int(ln): set(ids)
            for ln, ids in rec["summary"]["suppressions"].items()}
    kept: List[Finding] = []
    suppressed = 0
    by_file: Dict[str, List[Finding]] = {}
    for f in raw:
        by_file.setdefault(f.path, []).append(f)
    for display in sorted(by_file):
        k, s = filter_findings(by_file[display],
                               sup_by_display.get(display, {}), config)
        kept.extend(k)
        suppressed += s
    return kept, suppressed


def lint_program(paths: Sequence[str], config: Optional[LintConfig] = None,
                 rules: Optional[Sequence[Rule]] = None,
                 ) -> Tuple[LintResult, ProgramStats]:
    """The full ``dtx lint`` pipeline: per-module rules (cache-accelerated)
    + the cross-module program pass. Returns (result, cache stats)."""
    from datatunerx_tpu.analysis.rules import all_rules

    config = config or LintConfig()
    rules = all_rules() if rules is None else rules
    stats = ProgramStats()
    cache_path = config.resolve(config.cache) if config.cache else ""
    fingerprint = _fingerprint(config, rules)
    cache = _load_cache(cache_path, fingerprint)
    records: Dict[str, dict] = {}
    dirty = False
    for path in iter_python_files(paths, config):
        ap = os.path.abspath(path)
        if ap in records:
            continue
        display = _display_path(path, config)
        try:
            st = os.stat(ap)
        except OSError:
            continue
        stats.files += 1
        ent = cache["modules"].get(ap)
        if ent is not None and ent["mtime"] == st.st_mtime \
                and ent["size"] == st.st_size:
            records[ap] = {
                "display": display, "summary": ent["summary"],
                "findings": [Finding(**f) for f in ent["findings"]],
                "suppressed": ent["suppressed"]}
            stats.reused += 1
            continue
        rec = _analyze_file(ap, display, config, rules)
        records[ap] = rec
        cache["modules"][ap] = {
            "mtime": st.st_mtime, "size": st.st_size,
            "summary": rec["summary"],
            "findings": [f.to_json() for f in rec["findings"]],
            "suppressed": rec["suppressed"]}
        dirty = True
        stats.analyzed += 1

    result = LintResult()
    for ap in sorted(records):
        result.files += 1
        result.findings.extend(records[ap]["findings"])
        result.suppressed += records[ap]["suppressed"]

    if config.program:
        prog = ProgramGraph(records)
        wanted = {r.id for r in rules}
        raw: List[Finding] = []
        if "DTX001" in wanted and rule_enabled(config, "DTX001"):
            raw.extend(_program_dtx001(prog, config))
        if "DTX007" in wanted and rule_enabled(config, "DTX007"):
            raw.extend(_program_dtx007(prog))
        if "DTX009" in wanted and rule_enabled(config, "DTX009"):
            raw.extend(_program_dtx009(prog))
        if "DTX011" in wanted and rule_enabled(config, "DTX011"):
            raw.extend(_program_dtx011(prog))
        kept, suppressed = _filter_program_findings(raw, records, config)
        result.findings.extend(kept)
        result.suppressed += suppressed

    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if cache_path and dirty:
        _save_cache(cache_path, cache)
    return result, stats
