"""dtxlint — JAX-aware static analysis for the datatunerx-tpu codebase.

Pattern-based AST linting tuned to this repo's real bug history (see
CHANGELOG 0.6/0.7): host-sync calls in hot training/decode paths, jit
retrace storms, tracer-unsafe control flow, PRNG key reuse, mesh-axis
drift, lock discipline around gateway/prefetch threads, subprocess and
thread leaks, and device work at module import.

Entry points:

  python -m datatunerx_tpu.analysis [paths...]
  dtx lint [paths...]
  dtxlint [paths...]

Rules are self-contained visitor classes registered in
``datatunerx_tpu.analysis.rules``; per-rule docs live on each class.
Suppress a finding inline with ``# dtxlint: disable=DTX00N`` (comma
list, or ``all``), and carry pre-existing debt in a baseline file
(``--write-baseline``) so CI only blocks NEW findings.
"""

from datatunerx_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleContext,
    Rule,
    lint_paths,
    lint_source,
)
