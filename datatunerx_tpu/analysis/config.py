"""dtxlint configuration: defaults + the ``[tool.dtxlint]`` pyproject table.

The container's Python 3.10 has neither ``tomllib`` (3.11+) nor ``tomli``,
so when both imports fail a tiny TOML-subset reader handles the one table
we own: ``key = <python-ish literal>`` pairs (strings, ints, booleans, and
possibly-multiline lists of strings) under the ``[tool.dtxlint]`` header.
That subset is what this repo's pyproject actually contains; full TOML
files still parse correctly wherever a real parser is importable.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Optional, Sequence, Tuple

_SECTION = "tool.dtxlint"


@dataclasses.dataclass
class LintConfig:
    """Knobs the rules and runner consult. Field names match the pyproject
    keys with dashes normalized to underscores."""

    # baseline file path (relative to the config file's directory)
    baseline: str = "dtxlint-baseline.json"
    # directory/file basename fragments to skip while collecting sources
    exclude: Tuple[str, ...] = ("__pycache__", ".git", "build", "dist")
    # bare-name fnmatch patterns marking hot-path roots for DTX001
    hot_functions: Tuple[str, ...] = (
        "train_step", "eval_step", "decode_step", "generate_step",
    )
    # declared mesh axis names for DTX005; empty + mesh_module set → the
    # axes are extracted from *_AXES tuple assignments in that module
    mesh_axes: Tuple[str, ...] = ()
    mesh_module: str = ""
    # rule ids disabled globally (inline suppressions handle point FPs)
    disable: Tuple[str, ...] = ()
    # per-file rule disables as "glob:RULE1,RULE2" (or "glob:all") entries —
    # the dtxlint analogue of ruff's per-file-ignores, matched against the
    # finding's display path
    per_file_disable: Tuple[str, ...] = ()
    # cross-module program analysis (call graph over the linted package):
    # DTX001/DTX007/DTX009 follow calls across files when on
    program: bool = True
    # module-summary cache file ("" disables); relative to root. Keyed on
    # each file's mtime+size so repeat `dtx lint` runs skip re-analysis.
    cache: str = ".dtxlint-cache.json"
    # directory the config file was found in ("" = cwd); baseline and
    # mesh_module resolve against it
    root: str = ""

    def resolve(self, path: str) -> str:
        if not path or os.path.isabs(path) or not self.root:
            return path
        return os.path.join(self.root, path)


def _parse_toml_subset(text: str) -> dict:
    """Extract ``[tool.dtxlint]`` key/value pairs without a TOML parser.

    Values are read with ast.literal_eval after mapping TOML's bare
    true/false; anything fancier (dates, inline tables, dotted keys) is
    skipped rather than mis-read.
    """
    lines = text.splitlines()
    out: dict = {}
    in_section = False
    buf_key: Optional[str] = None
    buf_val: list = []

    def flush():
        nonlocal buf_key, buf_val
        if buf_key is None:
            return
        raw = "\n".join(buf_val).strip()
        raw = re.sub(r"\btrue\b", "True", raw)
        raw = re.sub(r"\bfalse\b", "False", raw)
        try:
            out[buf_key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            pass
        buf_key, buf_val = None, []

    for line in lines:
        stripped = line.strip()
        header = re.match(r"^\[(.+?)\]\s*$", stripped)
        if header and buf_key is None:
            in_section = header.group(1).strip() == _SECTION
            continue
        if not in_section:
            continue
        if buf_key is not None:
            buf_val.append(line.split("#", 1)[0] if '"' not in line else line)
            joined = "\n".join(buf_val)
            if joined.count("[") == joined.count("]"):
                flush()
            continue
        m = re.match(r"^([A-Za-z0-9_-]+)\s*=\s*(.*)$", stripped)
        if not m:
            continue
        key, val = m.group(1), m.group(2)
        if val.count("[") != val.count("]"):
            buf_key, buf_val = key, [val]
            continue
        buf_key, buf_val = key, [val]
        flush()
    flush()
    return out


def _read_table(path: str) -> dict:
    with open(path, "rb") as f:
        raw = f.read()
    try:
        import tomllib  # Python ≥ 3.11
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError:
            tomllib = None
    if tomllib is not None:
        table = tomllib.loads(raw.decode("utf-8"))
        for part in _SECTION.split("."):
            table = table.get(part, {})
        return table if isinstance(table, dict) else {}
    return _parse_toml_subset(raw.decode("utf-8"))


def find_pyproject(start: str) -> Optional[str]:
    """Walk up from ``start`` (file or directory) to the nearest
    pyproject.toml."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def load_config(start: str = ".",
                pyproject: Optional[str] = None) -> LintConfig:
    """Build a LintConfig from the nearest pyproject's ``[tool.dtxlint]``
    table (missing file or table → defaults)."""
    path = pyproject or find_pyproject(start)
    cfg = LintConfig()
    if path is None or not os.path.isfile(path):
        return cfg
    table = _read_table(path)
    fields = {f.name: f for f in dataclasses.fields(LintConfig)}
    kwargs: dict = {"root": os.path.dirname(os.path.abspath(path))}
    for key, value in table.items():
        name = key.replace("-", "_")
        if name not in fields or name == "root":
            continue
        if isinstance(value, list):
            value = tuple(str(v) for v in value)
        kwargs[name] = value
    return dataclasses.replace(cfg, **kwargs)


def mesh_axes_for(config: LintConfig) -> Tuple[str, ...]:
    """Declared mesh axis names: the configured list, else every string in
    ``*_AXES`` tuple/list assignments of the configured mesh module."""
    if config.mesh_axes:
        return tuple(config.mesh_axes)
    path = config.resolve(config.mesh_module)
    if not path or not os.path.isfile(path):
        return ()
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except SyntaxError:
        return ()
    axes: list = []
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(n.endswith("_AXES") or n == "AXES" for n in names):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    axes.append(elt.value)
    return tuple(dict.fromkeys(axes))


def rule_enabled(config: LintConfig, rule_id: str) -> bool:
    return rule_id not in set(config.disable)


def per_file_disabled(config: LintConfig, path: str) -> frozenset:
    """Rule ids disabled for ``path`` by ``per-file-disable`` entries
    ("glob:RULE1,RULE2" / "glob:all"), matched on /-normalized paths."""
    import fnmatch

    norm = path.replace(os.sep, "/")
    out: set = set()
    for entry in config.per_file_disable:
        glob, sep, rules = entry.partition(":")
        if not sep:
            continue
        if fnmatch.fnmatch(norm, glob.strip()) \
                or fnmatch.fnmatch(os.path.basename(norm), glob.strip()):
            out.update(r.strip() for r in rules.split(",") if r.strip())
    return frozenset(out)


__all__: Sequence[str] = (
    "LintConfig", "find_pyproject", "load_config", "mesh_axes_for",
    "per_file_disabled", "rule_enabled",
)
