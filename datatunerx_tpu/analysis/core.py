"""dtxlint framework: Finding/Rule/ModuleContext + the file runner.

A rule is a self-contained class with an ``id``, ``severity``, and a
``check(ctx) -> Iterable[Finding]``; the runner parses each file once,
hands every enabled rule the shared ModuleContext (AST, import aliases,
intra-module call graph, config), then filters findings through inline
``# dtxlint: disable=RULE`` suppressions. Baseline handling (carrying
pre-existing debt) lives in ``baseline.py``; this layer only reports.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.callgraph import (
    ModuleGraph,
    collect_aliases,
    resolve_name,
)
from datatunerx_tpu.analysis.config import (
    LintConfig,
    per_file_disabled,
    rule_enabled,
)

_SUPPRESS_RE = re.compile(r"#\s*dtxlint:\s*disable=([A-Za-z0-9_,\s]+)")
_HOT_BEGIN_RE = re.compile(r"#\s*dtxlint:\s*hot-begin\b")
_HOT_END_RE = re.compile(r"#\s*dtxlint:\s*hot-end\b")


def hot_region_spans(source: str) -> List[Tuple[int, int]]:
    """Inclusive (start, end) line ranges between ``# dtxlint: hot-begin``
    and ``# dtxlint: hot-end`` markers. An unmatched begin extends to EOF
    (the conservative direction for a hot-path rule); nested begins fold
    into the enclosing region."""
    spans: List[Tuple[int, int]] = []
    start = None
    lines = source.splitlines()
    for i, line in enumerate(lines, start=1):
        if _HOT_BEGIN_RE.search(line):
            if start is None:
                start = i
        elif _HOT_END_RE.search(line) and start is not None:
            spans.append((start, i))
            start = None
    if start is not None:
        spans.append((start, len(lines)))
    return spans


def module_name_for_path(path: str) -> Tuple[Optional[str], bool]:
    """(dotted module name, is_package) for a file inside a package tree —
    climbs parent directories while ``__init__.py`` exists. Files outside
    any package get (None, False); relative imports then stay unresolved."""
    ap = os.path.abspath(path)
    d, base = os.path.split(ap)
    if not base.endswith(".py"):
        return None, False
    is_package = base == "__init__.py"
    parts: List[str] = [] if is_package else [base[:-3]]
    while os.path.isfile(os.path.join(d, "__init__.py")):
        d, pkg = os.path.split(d)
        parts.insert(0, pkg)
    if not parts:
        return None, False
    return ".".join(parts), is_package


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col} {self.rule} {self.message}"

    def key(self) -> Tuple[str, str, str]:
        """Line-number-free identity used for baseline matching, so debt
        entries survive unrelated edits above them."""
        return (self.rule, self.path.replace(os.sep, "/"), self.message)

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path.replace(os.sep, "/"),
            "line": self.line, "col": self.col,
            "message": self.message, "severity": self.severity,
        }


class ModuleContext:
    """Per-file state shared by every rule (parse once, analyze N times)."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 config: LintConfig, module: Optional[str] = None,
                 is_package: bool = False):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.config = config
        self.module = module
        self.is_package = is_package
        self.aliases = collect_aliases(tree, module=module,
                                       is_package=is_package)
        self.hot_regions = hot_region_spans(source)
        # DTX007 cross-module candidates: resource handles whose only
        # disposition is "passed to a resolvable internal callee" — the
        # program pass adjudicates them against the callee's summary
        self.xescape_candidates: List[dict] = []
        self._graph: Optional[ModuleGraph] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def in_hot_region(self, line: int) -> bool:
        return any(s <= line <= e for s, e in self.hot_regions)

    @property
    def graph(self) -> ModuleGraph:
        if self._graph is None:
            self._graph = ModuleGraph(self.tree, self.aliases)
        return self._graph

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def resolve(self, node: ast.AST) -> Optional[str]:
        return resolve_name(node, self.aliases)


class Rule:
    """Base class; subclasses set ``id``/``name``/``severity`` and
    implement ``check``."""

    id = "DTX000"
    name = "unnamed"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message, self.severity)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0

    def extend(self, other: "LintResult"):
        self.findings.extend(other.findings)
        self.suppressed += other.suppressed
        self.files += other.files


def suppressions(source: str) -> Dict[int, Set[str]]:
    """Line number → rule ids disabled on that line (``all`` disables
    everything)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
    return out


def _default_rules() -> Sequence[Rule]:
    from datatunerx_tpu.analysis.rules import all_rules

    return all_rules()


def filter_findings(raw: Sequence[Finding], sup: Dict[int, Set[str]],
                    config: LintConfig) -> Tuple[List[Finding], int]:
    """Apply inline suppressions + per-file config disables to raw findings;
    returns (kept, suppressed_count). ``sup`` is a ``suppressions()`` map —
    passed in (rather than derived from source here) so the program-level
    pass can filter findings against CACHED modules without re-reading
    their files."""
    kept: List[Finding] = []
    suppressed = 0
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        pfd = per_file_disabled(config, f.path)
        if "all" in pfd or f.rule in pfd:
            continue  # config-level: not counted as inline suppression
        disabled = sup.get(f.line, ())
        if "all" in disabled or f.rule in disabled:
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def lint_source(source: str, path: str = "<string>",
                config: Optional[LintConfig] = None,
                rules: Optional[Sequence[Rule]] = None,
                module: Optional[str] = None,
                is_package: bool = False) -> LintResult:
    config = config or LintConfig()
    rules = _default_rules() if rules is None else rules
    result = LintResult(files=1)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        result.findings.append(Finding(
            "DTX000", path, e.lineno or 0, e.offset or 0,
            f"syntax error: {e.msg}", "error"))
        return result
    ctx = ModuleContext(path, source, tree, config, module=module,
                        is_package=is_package)
    raw: List[Finding] = []
    for rule in rules:
        if not rule_enabled(config, rule.id):
            continue
        raw.extend(rule.check(ctx))
    result.findings, result.suppressed = filter_findings(
        raw, suppressions(source), config)
    return result


def lint_file(path: str, config: Optional[LintConfig] = None,
              rules: Optional[Sequence[Rule]] = None,
              display_path: Optional[str] = None) -> LintResult:
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    module, is_package = module_name_for_path(path)
    return lint_source(source, path=display_path or path, config=config,
                       rules=rules, module=module, is_package=is_package)


def iter_python_files(paths: Sequence[str],
                      config: LintConfig) -> Iterable[str]:
    excluded = tuple(config.exclude)

    def skip(name: str) -> bool:
        return name.startswith(".") or name in excluded

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if not skip(d))
            for fn in sorted(files):
                if fn.endswith(".py") and not skip(fn):
                    yield os.path.join(root, fn)


def _display_path(path: str, config: LintConfig) -> str:
    """Project-root-relative path when the file lives under the config
    root (else cwd-relative, else as given) — finding keys must not depend
    on the invoker's cwd or absolute-vs-relative arguments, or baseline
    entries written by one invocation silently stop matching in another."""
    ap = os.path.abspath(path)
    for base in (config.root, os.getcwd()):
        if not base:
            continue
        try:
            rel = os.path.relpath(ap, base)
        except ValueError:  # different drive (windows)
            continue
        if not rel.startswith(".."):
            return rel
    return path


def lint_paths(paths: Sequence[str], config: Optional[LintConfig] = None,
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    config = config or LintConfig()
    rules = _default_rules() if rules is None else rules
    result = LintResult()
    for path in iter_python_files(paths, config):
        result.extend(lint_file(path, config=config, rules=rules,
                                display_path=_display_path(path, config)))
    return result
