"""Baseline handling: carry pre-existing findings so CI only blocks NEW debt.

The baseline is a checked-in JSON file of finding keys (rule, path,
message — deliberately line-number-free, so edits above a carried finding
don't invalidate it). ``partition`` matches multiset-style: two identical
findings need two baseline entries, so fixing one of a pair still
surfaces the other.

This repo's policy (ISSUE 4) is a PERMANENTLY EMPTY baseline — every
finding at head is fixed or inline-suppressed — but the mechanism exists
so future rules can land before their triage finishes.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Counter, List, Sequence, Tuple

from datatunerx_tpu.analysis.core import Finding

Key = Tuple[str, str, str]


def load_baseline(path: str) -> Counter:
    """Counter of carried finding keys; missing file → empty."""
    if not path or not os.path.isfile(path):
        return collections.Counter()
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    keys: Counter = collections.Counter()
    for entry in doc.get("findings", []):
        keys[(entry["rule"], entry["path"], entry["message"])] += 1
    return keys


def save_baseline(path: str, findings: Sequence[Finding]):
    doc = {
        "comment": "dtxlint baseline — regenerate with `dtxlint --write-baseline`",
        "findings": [
            {"rule": f.rule, "path": f.path.replace(os.sep, "/"),
             "message": f.message}
            for f in sorted(findings, key=lambda f: f.key())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def partition(findings: Sequence[Finding],
              baseline: Counter) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, baselined)."""
    budget = collections.Counter(baseline)
    new: List[Finding] = []
    carried: List[Finding] = []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            carried.append(f)
        else:
            new.append(f)
    return new, carried
