import sys

from datatunerx_tpu.analysis.cli import main

sys.exit(main())
