"""``python -m datatunerx_tpu.analysis.sanitizers`` == ``dtx san``."""

import sys

from datatunerx_tpu.analysis.sanitizers.cli import main

if __name__ == "__main__":
    sys.exit(main())
