"""Shared dtxsan plumbing: finding collection, site capture, suppressions.

Findings reuse ``analysis.core.Finding`` so the dtxlint baseline module
(`analysis/baseline.py`) partitions them unchanged; the extra runtime
evidence (acquisition stacks, leaked-thread stacks, compile sites) rides
in a parallel ``detail`` string keyed by the finding, because a frozen
Finding stays the stable (rule, path, message) identity the baseline and
the JSON contract key on.

Rule ids: SAN001 lock-order, SAN002 thread-leak, SAN003 compile-budget.

Inline suppression mirrors dtxlint's: ``# dtxsan: disable=SAN001`` on
the line a finding anchors to (the acquisition site, the spawn site, the
``with compile_budget`` line) silences it — with a reason in the
comment, per the empty-baseline policy.
"""

from __future__ import annotations

import linecache
import os
import re
import sys
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from datatunerx_tpu.analysis.core import Finding

SAN_LOCK_ORDER = "SAN001"
SAN_THREAD_LEAK = "SAN002"
SAN_COMPILE_BUDGET = "SAN003"

_SUPPRESS_RE = re.compile(r"#\s*dtxsan:\s*disable=([A-Za-z0-9_,\s]+)")

# the repository root every finding path is made relative to — the package
# lives at <root>/datatunerx_tpu/analysis/sanitizers
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# frames belonging to the sanitizer machinery or the interpreter's own
# locking layers are never "the" acquisition site
_SKIP_FILE_TOKENS = (
    os.sep + "sanitizers" + os.sep,
    os.sep + "threading.py",
    os.sep + "queue.py",
    os.sep + "concurrent" + os.sep + "futures" + os.sep,
    os.sep + "socketserver.py",
    os.sep + "logging" + os.sep,
)


def display_path(path: str) -> str:
    """Repo-root-relative, /-normalized — finding identity must not depend
    on the invoking cwd (same contract as dtxlint's _display_path)."""
    ap = os.path.abspath(path)
    try:
        rel = os.path.relpath(ap, REPO_ROOT)
    except ValueError:
        return path.replace(os.sep, "/")
    if rel.startswith(".."):
        return path.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


def _skippable(filename: str) -> bool:
    return any(tok in filename for tok in _SKIP_FILE_TOKENS)


def user_site(extra_skip: int = 0) -> Tuple[str, int]:
    """(abs file, line) of the nearest caller frame outside the sanitizer
    machinery and the stdlib locking layers. Cheap: sys._getframe walk,
    no stack object materialization."""
    try:
        frame = sys._getframe(2 + extra_skip)
    except ValueError:
        return ("<unknown>", 0)
    while frame is not None:
        fn = frame.f_code.co_filename
        if not _skippable(fn):
            return (fn, frame.f_lineno)
        frame = frame.f_back
    return ("<unknown>", 0)


def capture_stack(limit: int = 14) -> List[str]:
    """Trimmed human-readable stack of the CURRENT thread, innermost last,
    sanitizer/locking frames dropped. Only called on rare events (a new
    lock-order edge, a leak, a budget breach), never per acquisition."""
    out: List[str] = []
    for fr in traceback.extract_stack()[:-1]:
        if _skippable(fr.filename):
            continue
        out.append(f"{display_path(fr.filename)}:{fr.lineno} in {fr.name}"
                   + (f"\n    {fr.line}" if fr.line else ""))
    return out[-limit:]


def site_str(site: Tuple[str, int]) -> str:
    return f"{display_path(site[0])}:{site[1]}"


def suppressed_at(site: Tuple[str, int], rule: str) -> bool:
    """True when the source line at ``site`` carries an inline
    ``# dtxsan: disable=`` naming ``rule`` (or ``all``)."""
    path, line = site
    if not path or path.startswith("<") or line <= 0:
        return False
    text = linecache.getline(path, line)
    m = _SUPPRESS_RE.search(text)
    if not m:
        return False
    ids = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
    return "all" in ids or rule in ids


@dataclass
class SanFinding:
    """One runtime finding + its side-band evidence."""

    finding: Finding
    detail: str = ""


@dataclass
class Collector:
    """Process-global accumulation point all three sanitizers feed.

    ``add`` applies inline suppression at the anchoring site, so what the
    collector holds is already the post-suppression set (matching the
    dtxlint pipeline where suppression happens before baseline)."""

    findings: List[SanFinding] = field(default_factory=list)
    suppressed: int = 0
    _mu: threading.Lock = field(default_factory=threading.Lock,
                                repr=False)

    def add(self, rule: str, site: Tuple[str, int], message: str,
            detail: str = "", severity: str = "error") -> Optional[Finding]:
        """Record (or suppress) one finding anchored at ``site``; returns
        the Finding when it was kept."""
        if suppressed_at(site, rule):
            with self._mu:
                self.suppressed += 1
            return None
        f = Finding(rule, display_path(site[0]), site[1], 0, message,
                    severity)
        with self._mu:
            # idempotent re-runs (finalize called twice, or a leak seen by
            # both the per-test audit and the session sweep) must not
            # double-report one fact
            if any(sf.finding.key() == f.key()
                   and sf.finding.line == f.line
                   for sf in self.findings):
                return None
            self.findings.append(SanFinding(f, detail))
        return f

    def snapshot(self) -> Tuple[List[SanFinding], int]:
        with self._mu:
            return list(self.findings), self.suppressed

    def reset(self):
        with self._mu:
            self.findings.clear()
            self.suppressed = 0


COLLECTOR = Collector()

_VALID_CLASSES = ("lock", "thread", "compile")
_active: Tuple[str, ...] = ()


def parse_classes(spec: str) -> Tuple[str, ...]:
    """DTX_SAN value → sanitizer classes. "1"/"all"/"on" = everything."""
    spec = (spec or "").strip().lower()
    if not spec or spec in ("0", "off", "false"):
        return ()
    if spec in ("1", "all", "on", "true", "yes"):
        return _VALID_CLASSES
    out = tuple(tok.strip() for tok in spec.split(",")
                if tok.strip() in _VALID_CLASSES)
    return out


def active_classes() -> Tuple[str, ...]:
    return _active


def install_from_env(env: Optional[str] = None) -> Tuple[str, ...]:
    """Install the sanitizers DTX_SAN names (idempotent); returns the
    active class tuple. The global singletons in lockorder/threads/compile
    are used, so a whole process shares one graph/registry."""
    global _active
    classes = parse_classes(
        env if env is not None else os.environ.get("DTX_SAN", ""))
    if not classes:
        return _active
    if "lock" in classes:
        from datatunerx_tpu.analysis.sanitizers.lockorder import LOCK_SANITIZER

        LOCK_SANITIZER.install()
    if "thread" in classes:
        from datatunerx_tpu.analysis.sanitizers.threads import THREAD_SANITIZER

        THREAD_SANITIZER.install()
    if "compile" in classes:
        from datatunerx_tpu.analysis.sanitizers.compile import COMPILE_SANITIZER

        COMPILE_SANITIZER.install()
    _active = tuple(dict.fromkeys(_active + classes))
    return _active


def finalize(collector: Optional[Collector] = None) -> List[SanFinding]:
    """Run the end-of-session scans (lock-order cycles, module compile
    budgets) into the collector and return everything gathered. Safe to
    call more than once — the collector dedupes."""
    col = collector or COLLECTOR
    if "lock" in _active:
        from datatunerx_tpu.analysis.sanitizers.lockorder import LOCK_SANITIZER

        LOCK_SANITIZER.scan_into(col)
    if "compile" in _active:
        from datatunerx_tpu.analysis.sanitizers.compile import COMPILE_SANITIZER

        COMPILE_SANITIZER.scan_into(col)
    findings, _ = col.snapshot()
    return findings


def render(sf: SanFinding, with_detail: bool = True) -> str:
    text = sf.finding.render()
    if with_detail and sf.detail:
        text += "\n" + "\n".join("    " + ln
                                 for ln in sf.detail.splitlines())
    return text


__all__: Sequence[str] = (
    "COLLECTOR", "Collector", "SanFinding", "SAN_LOCK_ORDER",
    "SAN_THREAD_LEAK", "SAN_COMPILE_BUDGET", "REPO_ROOT",
    "active_classes", "capture_stack", "display_path", "finalize",
    "install_from_env", "parse_classes", "render", "site_str",
    "suppressed_at", "user_site",
)


def _fresh_collector() -> Collector:  # test helper
    return Collector()


def details_by_key(findings: List[SanFinding]) -> Dict[str, str]:
    """finding-render → detail map for the JSON report."""
    return {sf.finding.render(): sf.detail for sf in findings if sf.detail}
