"""pytest glue for dtxsan — loaded by tests/conftest.py when DTX_SAN is
set (``pytest_plugins`` stays conditional so a plain run pays nothing).

What it does:

  * ``pytest_configure`` installs the sanitizers DTX_SAN names and
    registers module compile budgets from
    ``DTX_SAN_MODULE_BUDGETS=path/substr=N,...``;
  * an autouse fixture snapshots live threads per test and runs the
    thread-leak audit at teardown — a leak FAILS that test, naming the
    spawn site (the audit fixture is function-scoped and autouse, so it
    finalizes after the test's own fixtures have cleaned up);
  * ``pytest_sessionfinish`` runs the end-of-run scans (lock-order
    cycles, module budgets), partitions against the dtxsan baseline
    (``DTX_SAN_BASELINE`` overrides the default path,
    ``DTX_SAN_NO_BASELINE=1`` ignores it), writes the raw report when
    ``DTX_SAN_REPORT`` names a path (for ``dtx san``), prints every new
    finding with its evidence, and forces a non-zero exit when anything
    new survived — a green suite with a pending deadlock is the failure
    mode this plugin exists to prevent.
"""

from __future__ import annotations

import os
import threading

import pytest

from datatunerx_tpu.analysis.sanitizers import report, runtime


def _parse_module_budgets(spec: str):
    out = []
    for tok in (spec or "").split(","):
        path, _, n = tok.partition("=")
        path = path.strip()
        n = n.strip()
        if path and n.lstrip("-").isdigit():
            out.append((path, int(n)))
    return out


def pytest_configure(config):
    classes = runtime.install_from_env()
    config._dtxsan_classes = classes
    if "compile" in classes:
        from datatunerx_tpu.analysis.sanitizers.compile import (
            register_module_budget,
        )

        for path, n in _parse_module_budgets(
                os.environ.get("DTX_SAN_MODULE_BUDGETS", "")):
            register_module_budget(path, n)


@pytest.fixture(autouse=True)
def _dtxsan_thread_audit(request):
    from datatunerx_tpu.analysis.sanitizers.threads import THREAD_SANITIZER

    if not THREAD_SANITIZER.installed:
        yield
        return
    before = set(threading.enumerate())
    yield
    leaks = THREAD_SANITIZER.audit(before, runtime.COLLECTOR,
                                   testid=request.node.nodeid)
    if leaks:
        pytest.fail("dtxsan thread-leak: "
                    + "; ".join(f.message for f in leaks), pytrace=False)


def pytest_sessionfinish(session, exitstatus):
    classes = runtime.active_classes()
    if not classes:
        return
    findings = runtime.finalize()
    suppressed = runtime.COLLECTOR.snapshot()[1]
    counters = {}
    if "compile" in classes:
        from datatunerx_tpu.analysis.sanitizers.compile import (
            COMPILE_SANITIZER,
        )

        counters = COMPILE_SANITIZER.counts()
    report_path = os.environ.get("DTX_SAN_REPORT", "")
    if report_path:
        report.write_raw(report_path, findings, suppressed, counters,
                         classes)
    evaluation = report.evaluate(
        findings, suppressed,
        baseline_path=os.environ.get("DTX_SAN_BASELINE") or None,
        no_baseline=os.environ.get("DTX_SAN_NO_BASELINE", "") == "1")
    text = report.render_text(evaluation, counters)
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.ensure_newline()
        tr.section("dtxsan", sep="=")
        tr.line(text)
    else:  # pragma: no cover - terminalreporter always present in practice
        print(text)
    if evaluation["failed"] and session.exitstatus == 0:
        session.exitstatus = 1
