"""dtxsan reporting: baseline partition, JSON contract, text rendering.

The baseline machinery is dtxlint's (`analysis/baseline.py`) verbatim —
SanFindings carry a plain ``Finding`` so ``partition`` works unchanged,
and the policy is the same: the checked-in baseline stays EMPTY; inline
``# dtxsan: disable=...`` with a reason is the only sanctioned way to
carry a finding.

Two artifact shapes:

  * the **raw report** (``write_raw``/``load_raw``) — every
    post-suppression finding with its evidence detail plus the compile
    counters; written by the pytest plugin (``DTX_SAN_REPORT=...``) so
    the ``dtx san`` CLI can re-partition under its own baseline flags
    without re-running the suite;
  * the **JSON contract doc** (``build_doc``) — mirrors ``dtx lint
    --format json``: ``{"version", "findings", "baselined",
    "suppressed", "failed"}`` plus dtxsan's ``counters``/``classes``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from datatunerx_tpu.analysis.baseline import load_baseline, partition
from datatunerx_tpu.analysis.core import Finding
from datatunerx_tpu.analysis.sanitizers.runtime import (
    REPO_ROOT,
    SanFinding,
    render,
)

JSON_SCHEMA_VERSION = 1
RAW_KIND = "dtxsan-raw"


def default_baseline_path() -> str:
    return os.path.join(REPO_ROOT, "dtxsan-baseline.json")


def default_report_path() -> str:
    return os.path.join(REPO_ROOT, ".dtxsan-report.json")


def evaluate(findings: Sequence[SanFinding], suppressed: int,
             baseline_path: Optional[str] = None,
             no_baseline: bool = False) -> Dict:
    """Partition findings against the baseline; ``failed`` iff anything
    NEW survives."""
    path = baseline_path or default_baseline_path()
    baseline = {} if no_baseline else load_baseline(path)
    plain = [sf.finding for sf in findings]
    new, carried = partition(plain, baseline)
    new_ids = {id(f) for f in new}
    new_sf = [sf for sf in findings if id(sf.finding) in new_ids]
    return {
        "new": new_sf,
        "baselined": len(carried),
        "suppressed": suppressed,
        "failed": bool(new_sf),
        "baseline_path": path,
    }


def build_doc(evaluation: Dict, counters: Optional[Dict[str, int]] = None,
              classes: Sequence[str] = (),
              pytest_exit: Optional[int] = None) -> Dict:
    doc = {
        "version": JSON_SCHEMA_VERSION,
        "findings": [dict(sf.finding.to_json(), detail=sf.detail)
                     for sf in evaluation["new"]],
        "baselined": evaluation["baselined"],
        "suppressed": evaluation["suppressed"],
        "failed": evaluation["failed"],
        "classes": list(classes),
        "counters": dict(counters or {}),
    }
    if pytest_exit is not None:
        doc["pytest_exit"] = pytest_exit
        doc["failed"] = doc["failed"] or pytest_exit != 0
    return doc


def render_text(evaluation: Dict, counters: Optional[Dict[str, int]] = None,
                with_detail: bool = True) -> str:
    lines: List[str] = []
    for sf in evaluation["new"]:
        lines.append(render(sf, with_detail=with_detail))
    new = len(evaluation["new"])
    summary = (f"dtxsan: {new} finding{'s' if new != 1 else ''}"
               f" ({evaluation['baselined']} baselined, "
               f"{evaluation['suppressed']} suppressed)")
    if counters:
        summary += (f"; compiles: {counters.get('lowerings', 0)} lowered"
                    f" / {counters.get('backend_compiles', 0)} backend")
    lines.append(summary)
    return "\n".join(lines)


# --------------------------------------------------------------- raw file
def write_raw(path: str, findings: Sequence[SanFinding], suppressed: int,
              counters: Optional[Dict[str, int]] = None,
              classes: Sequence[str] = ()):
    doc = {
        "version": JSON_SCHEMA_VERSION,
        "kind": RAW_KIND,
        "findings": [dict(sf.finding.to_json(), detail=sf.detail)
                     for sf in findings],
        "suppressed": suppressed,
        "counters": dict(counters or {}),
        "classes": list(classes),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_raw(path: str) -> Tuple[List[SanFinding], int, Dict[str, int],
                                 List[str]]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("kind") != RAW_KIND:
        raise ValueError(f"{path}: not a dtxsan raw report")
    findings = []
    for e in doc.get("findings", []):
        findings.append(SanFinding(
            Finding(e["rule"], e["path"], int(e.get("line", 0)),
                    int(e.get("col", 0)), e["message"],
                    e.get("severity", "error")),
            e.get("detail", "")))
    return (findings, int(doc.get("suppressed", 0)),
            dict(doc.get("counters", {})), list(doc.get("classes", [])))


__all__: Sequence[str] = (
    "JSON_SCHEMA_VERSION", "RAW_KIND", "build_doc", "default_baseline_path",
    "default_report_path", "evaluate", "load_raw", "render_text",
    "write_raw",
)
