"""SAN003 — the compile-budget sanitizer.

Counts XLA compiles through ``jax.monitoring``'s event-duration hooks
(fired synchronously inside the compile path, so the call stack still
shows which repo code triggered it) and enforces *declared budgets*:

  * ``with compile_budget(n):`` — the block may trigger at most ``n``
    fresh lowerings; a breach raises :class:`CompileBudgetExceeded`
    naming every compile site seen inside the window, and records a
    SAN003 finding anchored at the ``with`` line. ``compile_budget(0)``
    is how the PR 10 "adapter load/unload causes ZERO recompiles" and
    PR 14 memo-key invariants become hard suite-wide errors.
  * ``register_module_budget("path/substr", n)`` — bounds the total
    compiles attributed to sites in matching files over a whole run;
    checked by ``scan_into`` at session finish (the pytest plugin reads
    ``DTX_SAN_MODULE_BUDGETS=path=count,...``).

The budget metric is the **lowering** count (``jaxpr_to_mlir_module``
events): one per executable-cache miss, stable whether or not a
persistent compilation cache later satisfies the backend compile.
Backend compiles are tracked alongside for the report. jax is imported
lazily — the rest of ``analysis/`` stays importable with stdlib only.

NOTE for tests: building *inputs* (e.g. ``jnp.ones``) compiles tiny
programs too — construct inputs before entering the budget window.
"""

from __future__ import annotations

import _thread
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from datatunerx_tpu.analysis.sanitizers import runtime
from datatunerx_tpu.analysis.sanitizers.runtime import (
    REPO_ROOT,
    SAN_COMPILE_BUDGET,
    Collector,
    _skippable,
    site_str,
    user_site,
)

Site = Tuple[str, int]

_LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileBudgetExceeded(RuntimeError):
    """A ``compile_budget`` window saw more fresh compiles than declared."""


def _repo_site() -> Site:
    """First frame under the repo root (excluding sanitizer machinery) —
    the repo code that triggered this compile; ("<jax-internal>", 0)
    when the compile never passed through repo code."""
    try:
        frame = sys._getframe(2)
    except ValueError:
        return ("<jax-internal>", 0)
    while frame is not None:
        fn = frame.f_code.co_filename
        if fn.startswith(REPO_ROOT) and not _skippable(fn):
            return (fn, frame.f_lineno)
        frame = frame.f_back
    return ("<jax-internal>", 0)


class CompileSanitizer:
    def __init__(self):
        self.installed = False
        self.enabled = False
        self._mu = _thread.allocate_lock()
        self._lowerings = 0
        self._backend = 0
        # event log: (seq, site) per lowering, for budget-window slicing
        self._events: List[Site] = []
        self._site_counts: Dict[Site, int] = {}
        self._module_budgets: Dict[str, int] = {}

    # ------------------------------------------------------------ install
    def install(self):
        if self.installed:
            self.enabled = True
            return
        try:
            from jax import monitoring
        except Exception:  # pragma: no cover - jax always present here
            return
        san = self

        def _on_event(event, duration, *a, **kw):
            if not san.enabled:
                return
            if event == _BACKEND_EVENT:
                with san._mu:
                    san._backend += 1
                return
            if event != _LOWER_EVENT:
                return
            site = _repo_site()
            with san._mu:
                san._lowerings += 1
                san._events.append(site)
                san._site_counts[site] = san._site_counts.get(site, 0) + 1

        monitoring.register_event_duration_secs_listener(_on_event)
        self.installed = True
        self.enabled = True

    def uninstall(self):
        # jax.monitoring has no public per-listener removal; the listener
        # stays registered but goes inert
        self.enabled = False

    def reset(self):
        with self._mu:
            self._lowerings = 0
            self._backend = 0
            self._events.clear()
            self._site_counts.clear()

    # ------------------------------------------------------------ queries
    def counts(self) -> Dict[str, int]:
        with self._mu:
            return {"lowerings": self._lowerings,
                    "backend_compiles": self._backend}

    def event_index(self) -> int:
        with self._mu:
            return len(self._events)

    def events_since(self, index: int) -> List[Site]:
        with self._mu:
            return list(self._events[index:])

    # ------------------------------------------------------------ budgets
    def register_module_budget(self, path_substr: str, budget: int):
        with self._mu:
            self._module_budgets[path_substr] = int(budget)

    def scan_into(self, collector: Collector) -> List:
        out = []
        with self._mu:
            budgets = dict(self._module_budgets)
            counts = dict(self._site_counts)
        for substr, budget in sorted(budgets.items()):
            hits = {s: n for s, n in counts.items()
                    if substr in s[0].replace("\\", "/")}
            total = sum(hits.values())
            if total <= budget:
                continue
            top = sorted(hits.items(), key=lambda kv: (-kv[1],
                                                       site_str(kv[0])))[:6]
            sites = ", ".join(f"{site_str(s)} ({n}x)" for s, n in top)
            f = collector.add(
                SAN_COMPILE_BUDGET, (substr, 1),
                f"module compile budget exceeded: {total} compiles "
                f"attributed to '{substr}' (budget {budget}) — top sites: "
                f"{sites}",
                detail=f"per-site counts: "
                       + "; ".join(f"{site_str(s)}={n}"
                                   for s, n in sorted(
                                       hits.items(),
                                       key=lambda kv: site_str(kv[0]))))
            if f is not None:
                out.append(f)
        return out


COMPILE_SANITIZER = CompileSanitizer()


class compile_budget:
    """``with compile_budget(n, "label"):`` — assert at most ``n`` fresh
    XLA lowerings happen inside the block. Installs the compile listener
    on first use, so it works standalone (no DTX_SAN needed). A breach
    records a SAN003 finding at the ``with`` line and raises
    :class:`CompileBudgetExceeded` (suppress with
    ``# dtxsan: disable=SAN003`` on that line, or pass
    ``raise_on_exceed=False`` to only record)."""

    def __init__(self, budget: int, label: str = "",
                 raise_on_exceed: bool = True,
                 collector: Optional[Collector] = None):
        self.budget = int(budget)
        self.label = label
        self.raise_on_exceed = raise_on_exceed
        self.collector = collector
        self.seen = 0
        self.sites: List[Site] = []
        self._start = 0
        self._site: Site = ("<unknown>", 0)

    def __enter__(self) -> "compile_budget":
        COMPILE_SANITIZER.install()
        self._site = user_site()
        self._start = COMPILE_SANITIZER.event_index()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.sites = COMPILE_SANITIZER.events_since(self._start)
        self.seen = len(self.sites)
        if exc_type is not None or self.seen <= self.budget:
            return False
        counts: Dict[Site, int] = {}
        for s in self.sites:
            counts[s] = counts.get(s, 0) + 1
        top = sorted(counts.items(), key=lambda kv: (-kv[1],
                                                     site_str(kv[0])))[:6]
        sites = ", ".join(f"{site_str(s)} ({n}x)" for s, n in top)
        what = f" [{self.label}]" if self.label else ""
        msg = (f"compile budget exceeded{what}: {self.seen} fresh XLA "
               f"lowerings inside a compile_budget({self.budget}) window "
               f"— compile sites: {sites}")
        col = self.collector or runtime.COLLECTOR
        f = col.add(SAN_COMPILE_BUDGET, self._site, msg,
                    detail="each site is the nearest repo frame on the "
                           "stack when jax lowered a new program")
        if f is not None and self.raise_on_exceed:
            raise CompileBudgetExceeded(msg)
        return False


def register_module_budget(path_substr: str, budget: int):
    """Bound total compiles attributed to files matching ``path_substr``
    across the whole run (checked at session finish)."""
    COMPILE_SANITIZER.install()
    COMPILE_SANITIZER.register_module_budget(path_substr, budget)


__all__: Sequence[str] = ("COMPILE_SANITIZER", "CompileSanitizer",
                          "CompileBudgetExceeded", "compile_budget",
                          "register_module_budget")
