"""SAN002 — the thread-leak sanitizer.

``install()`` wraps ``threading.Thread.start`` to record each thread's
spawn site (the nearest non-stdlib caller frame — so for an executor it
names the ``submit()`` call site, not ``concurrent/futures``) plus a
trimmed spawn stack. The pytest plugin snapshots live threads before
each test and calls :meth:`ThreadLeakSanitizer.audit` at teardown: any
thread that appeared during the test and is still alive after a short
grace window is a leak, reported with the spawn site and the recorded
stack so the fix (a ``join`` on close, a stop ``Event``) is obvious.

Threads spawned from outside the repository (a library's internal pool
whose creation never passes through repo code) are counted but only
reported when ``DTX_SAN_FOREIGN=1`` — triage targets our own spawn
sites first. ``DTX_SAN_THREAD_GRACE`` (seconds, default 1.0) tunes the
grace window; ``# dtxsan: disable=SAN002`` on the spawn line suppresses.
"""

from __future__ import annotations

import os
import re
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.core import Finding
from datatunerx_tpu.analysis.sanitizers.runtime import (
    REPO_ROOT,
    SAN_THREAD_LEAK,
    Collector,
    capture_stack,
    site_str,
    user_site,
)

Site = Tuple[str, int]

# "worker-3" and "worker-7" are the same leak; strip trailing thread
# counters so the finding message (and hence its baseline key) is stable
_COUNTER_RE = re.compile(r"[-_]?\d+$")


class _SpawnInfo:
    __slots__ = ("site", "stack", "spawner")

    def __init__(self, site: Site, stack: List[str], spawner: str):
        self.site = site
        self.stack = stack
        self.spawner = spawner


class ThreadLeakSanitizer:
    def __init__(self):
        self.installed = False
        self._orig_start = None
        self._spawns: "weakref.WeakKeyDictionary[threading.Thread, _SpawnInfo]" = (
            weakref.WeakKeyDictionary())

    def install(self):
        if self.installed:
            return
        self._orig_start = threading.Thread.start
        san = self
        orig = self._orig_start

        def tracked_start(thread, *a, **kw):
            san._spawns[thread] = _SpawnInfo(
                user_site(), capture_stack(),
                threading.current_thread().name)
            return orig(thread, *a, **kw)

        threading.Thread.start = tracked_start
        self.installed = True

    def uninstall(self):
        if self.installed and self._orig_start is not None:
            threading.Thread.start = self._orig_start
            self._orig_start = None
        self.installed = False

    def spawn_info(self, thread: threading.Thread) -> Optional[_SpawnInfo]:
        return self._spawns.get(thread)

    # ------------------------------------------------------------- audit
    @staticmethod
    def _grace(default: float = 1.0) -> float:
        try:
            return float(os.environ.get("DTX_SAN_THREAD_GRACE", default))
        except ValueError:
            return default

    @staticmethod
    def _in_repo(site: Site) -> bool:
        return site[0].startswith(REPO_ROOT + os.sep)

    def leaked_since(self, before: Set[threading.Thread],
                     grace: Optional[float] = None
                     ) -> List[threading.Thread]:
        """Threads alive now that were not alive at the snapshot, after
        waiting up to ``grace`` seconds for stragglers to finish."""
        grace = self._grace() if grace is None else grace
        me = threading.current_thread()
        deadline = time.monotonic() + max(0.0, grace)
        while True:
            leaked = [t for t in threading.enumerate()
                      if t not in before and t is not me and t.is_alive()
                      and not getattr(t, "_dtxsan_allowed", False)]
            if not leaked or time.monotonic() >= deadline:
                return leaked
            time.sleep(0.02)

    def audit(self, before: Set[threading.Thread], collector: Collector,
              testid: str = "", grace: Optional[float] = None
              ) -> List[Finding]:
        """Report every thread leaked past ``before``; returns the kept
        (non-suppressed, non-foreign) findings."""
        out: List[Finding] = []
        foreign_ok = os.environ.get("DTX_SAN_FOREIGN", "") == "1"
        for t in self.leaked_since(before, grace):
            info = self._spawns.get(t)
            site = info.site if info else ("<unknown>", 0)
            if info and not self._in_repo(site) and not foreign_ok:
                continue  # library-internal pool; opt in via DTX_SAN_FOREIGN
            base_name = _COUNTER_RE.sub("", t.name) or t.name
            msg = (f"thread leaked: {base_name!r} spawned at "
                   f"{site_str(site)} is still alive at teardown — join it "
                   "on close or give it a stop Event the cleanup sets")
            detail_lines = []
            if testid:
                detail_lines.append(f"first leaked past: {testid}")
            if info:
                detail_lines.append(f"spawned by thread {info.spawner!r}; "
                                    "spawn stack:")
                detail_lines.extend("  " + ln for ln in info.stack)
            f = collector.add(SAN_THREAD_LEAK, site, msg,
                              detail="\n".join(detail_lines))
            if f is not None:
                out.append(f)
        return out


def allow_thread(thread: threading.Thread) -> threading.Thread:
    """Mark one thread as intentionally outliving test teardown (e.g. a
    session-scoped fixture's server thread that a later finalizer joins)."""
    thread._dtxsan_allowed = True
    return thread


THREAD_SANITIZER = ThreadLeakSanitizer()

__all__: Sequence[str] = ("THREAD_SANITIZER", "ThreadLeakSanitizer",
                          "allow_thread")
