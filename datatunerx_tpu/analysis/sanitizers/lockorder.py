"""SAN001 — the runtime lock-order sanitizer.

``install()`` replaces ``threading.Lock`` / ``threading.RLock`` with
factories returning tracked wrappers (``threading.Condition``/``Event``/
``queue.Queue`` build on those factories, so their internal locks are
tracked for free). Every **unbounded blocking** acquisition made while
the thread already holds other tracked locks records a directed edge

    (allocation site of a held lock) → (allocation site of the acquired)

in one process-global graph. Nodes are allocation sites — not instances
— so the ordering generalizes across objects and test runs; a
try-acquire or a finite-timeout acquire cannot deadlock and records
nothing. The first observation of an edge captures the acquiring
thread's stack (which shows BOTH sides: the ``with`` holding the first
lock upstream and the acquisition being made), so a cycle report can
print both acquisition stacks.

``scan_into`` (called by ``runtime.finalize``) turns the graph into
findings:

  * a cycle ⇒ potential ABBA deadlock, reported once per distinct node
    set with every edge's stack in the detail;
  * ``# dtxsan: order(N)`` / ``order(group:N)`` on an allocation line
    declares a rank — consistent low→high edges are JUSTIFIED (removed
    from the cycle graph), a high→low edge is an immediate
    declared-order violation;
  * a same-thread blocking re-acquisition of a non-reentrant Lock is a
    guaranteed self-deadlock, reported immediately and raised as
    ``LockOrderViolation`` so the suite fails instead of hanging.

Inline ``# dtxsan: disable=SAN001`` on the acquisition line suppresses,
as everywhere in dtxsan.
"""

from __future__ import annotations

import _thread
import linecache
import os
import re
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.sanitizers import runtime
from datatunerx_tpu.analysis.sanitizers.runtime import (
    SAN_LOCK_ORDER,
    Collector,
    capture_stack,
    site_str,
    user_site,
)

Site = Tuple[str, int]

_ORDER_RE = re.compile(
    r"#\s*dtxsan:\s*order\(\s*(?:([A-Za-z0-9_.-]+)\s*:)?\s*(-?\d+)\s*\)")


def _repo_alloc(site: Site) -> bool:
    return site[0].startswith(runtime.REPO_ROOT + os.sep)


class LockOrderViolation(RuntimeError):
    """Raised on a guaranteed self-deadlock (blocking re-acquisition of a
    non-reentrant Lock by the thread that already holds it)."""


class _EdgeSample:
    __slots__ = ("holder_site", "acq_site", "stack", "thread", "count")

    def __init__(self, holder_site: Site, acq_site: Site,
                 stack: List[str], thread: str):
        self.holder_site = holder_site
        self.acq_site = acq_site
        self.stack = stack
        self.thread = thread
        self.count = 1


class _TrackedLock:
    """Duck-typed stand-in for Lock/RLock: tracked acquire/release/with;
    everything else (``_is_owned``, ``_release_save`` for Condition,
    ``_at_fork_reinit``) delegates to the real lock underneath."""

    __slots__ = ("_dtxsan_inner", "_dtxsan_alloc", "_dtxsan_reentrant",
                 "_dtxsan_san", "__weakref__")

    def __init__(self, inner, alloc: Site, reentrant: bool,
                 san: "LockOrderSanitizer"):
        self._dtxsan_inner = inner
        self._dtxsan_alloc = alloc
        self._dtxsan_reentrant = reentrant
        self._dtxsan_san = san

    def acquire(self, blocking=True, timeout=-1):
        san = self._dtxsan_san
        if san.enabled:
            unbounded = blocking and (timeout is None or timeout < 0)
            if unbounded:
                san._before_blocking_acquire(self)
        ok = self._dtxsan_inner.acquire(blocking, -1 if timeout is None
                                        else timeout)
        if ok and san.enabled:
            san._push_held(self)
        return ok

    def release(self):
        self._dtxsan_inner.release()
        self._dtxsan_san._pop_held(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._dtxsan_inner.locked()

    def __getattr__(self, name):
        return getattr(self._dtxsan_inner, name)

    def __repr__(self):
        return (f"<dtxsan tracked {'RLock' if self._dtxsan_reentrant else 'Lock'}"
                f" from {site_str(self._dtxsan_alloc)} {self._dtxsan_inner!r}>")


class LockOrderSanitizer:
    def __init__(self):
        self.enabled = False
        self._orig_lock = None
        self._orig_rlock = None
        # the registry mutex must be a RAW lock — a tracked one would
        # recurse into edge recording forever
        self._mu = _thread.allocate_lock()
        self._edges: Dict[Tuple[Site, Site], _EdgeSample] = {}
        self._tls = threading.local()

    # ------------------------------------------------------------ install
    def install(self):
        if self.enabled:
            return
        if self._orig_lock is None:
            self._orig_lock = threading.Lock
            self._orig_rlock = threading.RLock
            san = self

            # only locks ALLOCATED by repo code are tracked; a library's
            # internal locks (jax's compile caches, grpc pools) get the
            # raw primitive back — their ordering is not ours to police
            # and tracking them would drown the graph in foreign edges
            def tracked_lock():
                site = user_site()
                if not _repo_alloc(site):
                    return san._orig_lock()
                return _TrackedLock(san._orig_lock(), site, False, san)

            def tracked_rlock():
                site = user_site()
                if not _repo_alloc(site):
                    return san._orig_rlock()
                return _TrackedLock(san._orig_rlock(), site, True, san)

            threading.Lock = tracked_lock
            threading.RLock = tracked_rlock
        self.enabled = True

    def uninstall(self):
        """Stop tracking and restore the factories. Wrappers already handed
        out keep delegating (their fast path checks ``enabled``)."""
        self.enabled = False
        if self._orig_lock is not None:
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
            self._orig_lock = self._orig_rlock = None

    def reset(self):
        with self._mu:
            self._edges.clear()

    # ----------------------------------------------------------- tracking
    def _held(self) -> List[Tuple[_TrackedLock, Site]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def _push_held(self, lock: _TrackedLock):
        self._held().append((lock, user_site()))

    def _pop_held(self, lock: _TrackedLock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    def _before_blocking_acquire(self, lock: _TrackedLock):
        held = self._held()
        if not held:
            return
        for h, _site in held:
            if h is lock:
                if lock._dtxsan_reentrant:
                    return  # RLock re-entry: no ordering information
                acq = user_site()
                f = runtime.COLLECTOR.add(
                    SAN_LOCK_ORDER, acq,
                    "guaranteed self-deadlock: this thread blocks "
                    "re-acquiring the non-reentrant Lock allocated at "
                    f"{site_str(lock._dtxsan_alloc)} which it already "
                    "holds — use an RLock or release first",
                    detail="\n".join(capture_stack()))
                if f is not None:
                    raise LockOrderViolation(f.message)
                return
        acq = user_site()
        stack: Optional[List[str]] = None
        for h, h_site in held:
            a, b = h._dtxsan_alloc, lock._dtxsan_alloc
            if a == b:
                continue  # same allocation site: parent/child of one class
            key = (a, b)
            with self._mu:
                sample = self._edges.get(key)
                if sample is not None:
                    sample.count += 1
                    continue
            if stack is None:  # one capture serves every new edge here
                stack = capture_stack()
            with self._mu:
                self._edges.setdefault(key, _EdgeSample(
                    h_site, acq, stack, threading.current_thread().name))

    # ------------------------------------------------------------- report
    @staticmethod
    def _declared_order(site: Site) -> Optional[Tuple[str, int]]:
        text = linecache.getline(site[0], site[1])
        m = _ORDER_RE.search(text)
        if not m:
            return None
        return (m.group(1) or "default", int(m.group(2)))

    def scan_into(self, collector: Collector) -> List:
        """Cycle + declared-order scan over the recorded graph."""
        with self._mu:
            edges = dict(self._edges)
        ranks: Dict[Site, Optional[Tuple[str, int]]] = {}
        for a, b in edges:
            for s in (a, b):
                if s not in ranks:
                    ranks[s] = self._declared_order(s)
        graph: Dict[Site, Set[Site]] = {}
        out = []
        for (a, b), e in sorted(edges.items(),
                                key=lambda kv: (site_str(kv[0][0]),
                                                site_str(kv[0][1]))):
            ra, rb = ranks.get(a), ranks.get(b)
            if ra and rb and ra[0] == rb[0]:
                if ra[1] < rb[1]:
                    continue  # consistent with the declared order: justified
                f = collector.add(
                    SAN_LOCK_ORDER, e.acq_site,
                    f"declared lock order violated: lock {site_str(b)} "
                    f"(order {rb[1]}) acquired while holding "
                    f"{site_str(a)} (order {ra[1]}, group {ra[0]}) — "
                    "declared ranks must only be taken low-to-high",
                    detail=self._edge_detail(e))
                if f is not None:
                    out.append(f)
                continue
            graph.setdefault(a, set()).add(b)
        out.extend(self._cycle_findings(graph, edges, collector))
        return out

    @staticmethod
    def _edge_detail(e: _EdgeSample, header: str = "") -> str:
        lines = []
        if header:
            lines.append(header)
        lines.append(f"held since {site_str(e.holder_site)}, acquired at "
                     f"{site_str(e.acq_site)} on thread {e.thread!r} "
                     f"(seen {e.count}x); acquisition stack:")
        lines.extend("  " + ln for ln in e.stack)
        return "\n".join(lines)

    def _cycle_findings(self, graph, edges, collector: Collector) -> List:
        out = []
        seen_cycles: Set[frozenset] = set()
        for (a, b) in sorted(edges, key=lambda k: (site_str(k[0]),
                                                   site_str(k[1]))):
            if b not in graph.get(a, ()):  # justified / violation edge
                continue
            path = self._shortest_path(graph, b, a)
            if path is None:
                continue
            cycle = [a] + path  # a -> b -> ... -> a
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            chain = " -> ".join(site_str(s) for s in cycle)
            e = edges[(a, b)]
            # the return edge closing the cycle (last hop back to a) is
            # the "opposite order" the message names
            back = edges.get((path[-2] if len(path) >= 2 else b, a))
            back_at = site_str(back.acq_site) if back else "?"
            msg = (f"potential deadlock: lock-order cycle {chain} — lock "
                   f"{site_str(b)} acquired here while holding "
                   f"{site_str(a)}, and the opposite order was observed "
                   f"at {back_at}; acquire these locks in one global "
                   "order, or declare ranks with `# dtxsan: order(N)`")
            detail_parts = []
            for i in range(len(cycle) - 1):
                ce = edges.get((cycle[i], cycle[i + 1]))
                if ce is not None:
                    detail_parts.append(self._edge_detail(
                        ce, header=f"edge {site_str(cycle[i])} -> "
                                   f"{site_str(cycle[i + 1])}:"))
            f = collector.add(SAN_LOCK_ORDER, e.acq_site, msg,
                              detail="\n".join(detail_parts))
            if f is not None:
                out.append(f)
        return out

    @staticmethod
    def _shortest_path(graph, src: Site, dst: Site) -> Optional[List[Site]]:
        """BFS path src..dst (inclusive); None when unreachable."""
        if src == dst:
            return [src]
        prev: Dict[Site, Site] = {}
        queue = [src]
        seen = {src}
        while queue:
            cur = queue.pop(0)
            for nxt in sorted(graph.get(cur, ()),
                              key=lambda s: site_str(s)):
                if nxt in seen:
                    continue
                prev[nxt] = cur
                if nxt == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                seen.add(nxt)
                queue.append(nxt)
        return None

    # test / forensics helpers
    def edge_count(self) -> int:
        with self._mu:
            return len(self._edges)


LOCK_SANITIZER = LockOrderSanitizer()

__all__: Sequence[str] = ("LOCK_SANITIZER", "LockOrderSanitizer",
                          "LockOrderViolation")
