"""dtxsan — the runtime sanitizer plane (ISSUE 19).

dtxlint (``analysis/``) proves concurrency discipline STATICALLY; this
package proves the dynamic half under the real test and chaos-replay
harnesses. Three sanitizers, all stdlib-only (jax is imported lazily and
only by the compile sanitizer):

  * **SAN001 lock-order** (`lockorder.py`) — wraps ``threading.Lock`` /
    ``RLock`` construction so every unbounded blocking acquisition
    records held→acquired edges in a global lock-order graph keyed by
    the locks' allocation sites; cycles are potential ABBA deadlocks and
    are reported with BOTH acquisition stacks. ``# dtxsan: order(N)`` on
    an allocation line declares a rank (consistent low→high edges are
    justified; a high→low acquisition is an immediate violation).
  * **SAN002 thread-leak** (`threads.py`) — per-test teardown audit of
    threads that outlive the test, each named by the spawn site recorded
    when ``Thread.start`` ran.
  * **SAN003 compile-budget** (`compile.py`) — counts XLA compiles via
    the ``jax.monitoring`` events and enforces declared budgets:
    ``with compile_budget(0):`` turns the engine-memo "load/unload
    causes ZERO recompiles" invariant into a hard error naming the
    compile sites; module-level budgets bound a whole run.

Activation: ``DTX_SAN=1`` (all) or a comma list of ``lock,thread,
compile`` — read by the pytest plugin (`plugin.py`, loaded from
tests/conftest.py) and by ``dtx replay`` for the chaos harness. ``dtx
san`` (`cli.py`) wraps a pytest run and applies the dtxlint exit-code /
``--format json`` contract; findings reuse ``analysis.baseline`` (the
policy baseline stays EMPTY) and honor inline
``# dtxsan: disable=SANxxx`` suppressions.
"""

from datatunerx_tpu.analysis.sanitizers.compile import (  # noqa: F401
    CompileBudgetExceeded,
    compile_budget,
    register_module_budget,
)
from datatunerx_tpu.analysis.sanitizers.runtime import (  # noqa: F401
    active_classes,
    install_from_env,
)

__all__ = [
    "CompileBudgetExceeded",
    "compile_budget",
    "register_module_budget",
    "active_classes",
    "install_from_env",
]
