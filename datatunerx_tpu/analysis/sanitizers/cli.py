"""``dtx san`` — run pytest under the sanitizers and report like dtxlint.

Wraps a pytest invocation (child process, so the wrapper's own
interpreter stays un-instrumented), collects the raw report the plugin
writes, partitions it against the dtxsan baseline, and emits the same
contract as ``dtx lint``: human text or ``--format json`` with
``{"version", "findings", "baselined", "suppressed", "failed"}``; exit
0 clean / 1 findings-or-test-failure / 2 usage-or-infrastructure error.

    dtx san                                   # whole suite, all sanitizers
    dtx san --san lock,thread -- tests/test_gateway.py -q
    dtx san --module-budget datatunerx_tpu/serving=64 -- tests/
    dtx san --from-report .dtxsan-report.json --format json

``--write-baseline`` snapshots current findings into the baseline file —
policy here keeps that file EMPTY (fix or inline-annotate instead), but
the mechanism matches dtxlint's for rule rollouts.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from datatunerx_tpu.analysis.baseline import save_baseline
from datatunerx_tpu.analysis.sanitizers import report


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtx san",
        description="Run pytest under the dtxsan runtime sanitizers "
                    "(SAN001 lock-order, SAN002 thread-leak, SAN003 "
                    "compile-budget).")
    p.add_argument("--san", default="1", metavar="CLASSES",
                   help="sanitizer classes: 1/all or a comma list of "
                        "lock,thread,compile (default: all)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline path (default: dtxsan-baseline.json at "
                        "the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline and exit 0")
    p.add_argument("--report", default=None, metavar="FILE",
                   help="where the raw report is written "
                        "(default: .dtxsan-report.json at the repo root)")
    p.add_argument("--from-report", default=None, metavar="FILE",
                   help="skip the pytest run; evaluate an existing raw "
                        "report")
    p.add_argument("--module-budget", action="append", default=[],
                   metavar="PATH=N",
                   help="module compile budget (repeatable); requires the "
                        "compile sanitizer")
    p.add_argument("--no-detail", action="store_true",
                   help="omit evidence stacks from text output")
    p.add_argument("pytest_args", nargs=argparse.REMAINDER,
                   help="arguments after -- go to pytest verbatim "
                        "(default: tests/ -q)")
    return p


def _run_pytest(args, report_path: str) -> int:
    pytest_args = [a for a in args.pytest_args if a != "--"]
    if not pytest_args:
        pytest_args = ["tests/", "-q"]
    env = dict(os.environ)
    env["DTX_SAN"] = args.san
    env["DTX_SAN_REPORT"] = report_path
    if args.baseline:
        env["DTX_SAN_BASELINE"] = args.baseline
    if args.no_baseline:
        env["DTX_SAN_NO_BASELINE"] = "1"
    budgets = [b for b in args.module_budget if "=" in b]
    if budgets:
        env["DTX_SAN_MODULE_BUDGETS"] = ",".join(budgets)
    cmd = [sys.executable, "-m", "pytest"] + pytest_args
    return subprocess.call(cmd, env=env)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    for b in args.module_budget:
        if "=" not in b or not b.split("=", 1)[1].strip().lstrip("-").isdigit():
            print(f"dtx san: bad --module-budget {b!r} (want PATH=N)",
                  file=sys.stderr)
            return 2

    report_path = args.report or report.default_report_path()
    pytest_exit: Optional[int] = None
    if args.from_report:
        report_path = args.from_report
    else:
        pytest_exit = _run_pytest(args, report_path)
    try:
        findings, suppressed, counters, classes = report.load_raw(
            report_path)
    except (OSError, ValueError, KeyError) as e:
        print(f"dtx san: cannot read report {report_path}: {e}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline or report.default_baseline_path()
        save_baseline(path, [sf.finding for sf in findings])
        print(f"dtx san: wrote {len(findings)} finding(s) to {path}")
        return 0

    evaluation = report.evaluate(findings, suppressed,
                                 baseline_path=args.baseline,
                                 no_baseline=args.no_baseline)
    doc = report.build_doc(evaluation, counters, classes,
                           pytest_exit=pytest_exit)
    if args.format == "json":
        print(json.dumps(doc, indent=1))
    else:
        print(report.render_text(evaluation, counters,
                                 with_detail=not args.no_detail))
        if pytest_exit not in (None, 0):
            print(f"dtx san: pytest exited {pytest_exit}")
    return 1 if doc["failed"] else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
