"""DTX011: static lock-order inversion — the compile-time mirror of the
runtime SAN001 sanitizer (`analysis/sanitizers/lockorder.py`).

A lock here is the DTX009 naming heuristic (``with self._lock:``,
``_POOL_LOCK`` — see ``rules/blocking.py``), contextualized to a stable
identity so orders compare across functions and modules:

    ``self._lock`` in class C of module M      →  ``M.C._lock``
    bare/module-level ``_POOL_LOCK`` in M      →  ``M._POOL_LOCK``

Two sources of ordering edges:

  * **lexical** — a lock-guarded ``with`` nested inside another in the
    same function body acquires inner while holding outer;
  * **call-chain** (program pass in ``analysis/program.py``) — a call
    made under a lock to a function whose reachable closure (over
    call-only edges, same reachability DTX009 uses) acquires another
    lock; the edge lands on the call site and the finding names the
    acquiring LEAF, like DTX009 names its blocking leaf.

A cycle in the resulting order graph is a potential ABBA deadlock. This
per-module rule reports cycles provable from one file's lexical edges
alone; the program pass reports every cycle that needs a call edge or a
second module (and skips the purely-lexical single-module ones, so
nothing is reported twice). Suppress with ``# dtxlint: disable=DTX011``
— and tell the runtime sanitizer the same story with
``# dtxsan: order(N)`` ranks on the allocation sites.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule
from datatunerx_tpu.analysis.rules.blocking import lock_name

Edge = Tuple[str, str]


def lock_context_id(module: Optional[str], cls: Optional[str],
                    name: str) -> str:
    """Stable cross-module identity for a lock name seen in source."""
    mod = module or "?"
    if name.startswith(("self.", "cls.")):
        attr = name.split(".", 1)[1]
        return f"{mod}.{cls}.{attr}" if cls else f"{mod}.{attr}"
    return f"{mod}.{name}"


def _with_lock_ids(ctx: ModuleContext, cls: Optional[str],
                   node: ast.AST) -> List[str]:
    """Contextualized ids of the lock-guarded items of one with-stmt, in
    acquisition order (multi-item withs acquire left to right)."""
    out: List[str] = []
    for item in node.items:
        name = lock_name(item.context_expr)
        if name:
            out.append(lock_context_id(ctx.module, cls, name))
    return out


def function_lock_info(ctx: ModuleContext, info
                       ) -> Tuple[List[List], List[List]]:
    """(acquires, lexical edges) for one function:
    acquires = [[lock_id, line], ...] for every lock-guarded with;
    edges    = [[outer_id, inner_id, line], ...] for every acquisition
    made while another lock is lexically held (line = inner with)."""
    acquires: List[List] = []
    edges: List[List] = []

    def visit(node: ast.AST, held: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue  # nested def runs later, maybe without the lock
            inner_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                ids = _with_lock_ids(ctx, info.cls, child)
                if ids:
                    inner_held = list(held)
                    for lid in ids:
                        acquires.append([lid, child.lineno])
                        for h in inner_held:
                            if h != lid:
                                edges.append([h, lid, child.lineno])
                        inner_held.append(lid)
            visit(child, inner_held)

    visit(info.node, [])
    return acquires, edges


class LockOrderInversion(Rule):
    id = "DTX011"
    name = "lock-order-inversion"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        # edge → (line of the inner acquisition, holder qualname)
        edges: Dict[Edge, Tuple[int, str]] = {}
        for qualname in sorted(ctx.graph.functions):
            info = ctx.graph.functions[qualname]
            _acq, fn_edges = function_lock_info(ctx, info)
            for a, b, line in fn_edges:
                edges.setdefault((a, b), (line, qualname))
        out: List[Finding] = []
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles: Set[frozenset] = set()
        for (a, b) in sorted(edges):
            path = shortest_path(graph, b, a)
            if path is None:
                continue
            cycle = [a] + path
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            line, qualname = edges[(a, b)]
            back = edges.get((path[-2] if len(path) >= 2 else b, a))
            back_at = f"line {back[0]} in {back[1]}" if back else "?"
            chain = " -> ".join(cycle)
            out.append(Finding(
                self.id, ctx.path, line, 0,
                f"lock-order inversion: {b} acquired in {qualname} while "
                f"holding {a}, but the opposite order is taken at "
                f"{back_at} (cycle {chain}) — two threads interleaving "
                "these paths deadlock; acquire in one global order",
                self.severity))
        return out


def shortest_path(graph: Dict[str, Set[str]], src: str,
                  dst: str) -> Optional[List[str]]:
    """BFS path src..dst inclusive over a lock-id graph; None when
    unreachable. Shared with the program pass."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    queue = [src]
    seen = {src}
    while queue:
        cur = queue.pop(0)
        for nxt in sorted(graph.get(cur, ())):
            if nxt in seen:
                continue
            prev[nxt] = cur
            if nxt == dst:
                path = [dst]
                while path[-1] != src:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            seen.add(nxt)
            queue.append(nxt)
    return None
