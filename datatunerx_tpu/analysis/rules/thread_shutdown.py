"""DTX012: daemon thread started by a class with no shutdown evidence.

DTX007 deliberately exempts ``daemon=True`` threads — they cannot block
interpreter exit, which is that rule's severity bar. But a daemon worker
a class starts and can never stop has its own failure mode: it dies
MID-OPERATION at interpreter exit (half-written spill file, orphaned
lease), keeps ticking against a torn-down object during tests (the
thread-leak sanitizer SAN002 sees exactly these), and pins the object
alive through its bound-method target. The discipline this rule checks:
a class that starts a daemon ``threading.Thread``/``Timer`` must show
SOME shutdown path — any method that

  * ``join()``s / ``cancel()``s the stored handle (or a local derived
    from it, two data-flow hops like DTX007), or
  * ``set()``s an event-ish ``self`` attribute (``self._stop.set()`` —
    the loop-checks-an-Event idiom; names containing stop/shut/exit/
    quit/done/close/drain/event/halt/kill count), or
  * for a locally-created handle, joins/cancels it in the same function —
    or the handle escapes into a ``self`` attribute (``self.X = t`` /
    ``self.X.append(t)``) that some method joins/cancels.

``daemon=True`` in the constructor or a later ``x.daemon = True``
assignment both count as daemonizing. Threads that are never
``start()``ed anywhere in the class are ignored. Module-level functions
are out of scope (no lifecycle to hang cleanup on — DTX007 already
covers non-daemon handles there). Suppress with
``# dtxlint: disable=DTX012`` plus a reason when the worker is
genuinely fire-and-forget for the process lifetime.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule
from datatunerx_tpu.analysis.rules.concurrency import ResourceLeak, _self_attr

_THREAD_TYPES = {"threading.Thread", "threading.Timer"}
_STOP_METHODS = {"join", "cancel", "shutdown"}
_EVENTISH = ("stop", "shut", "exit", "quit", "done", "close", "drain",
             "event", "halt", "kill")

_RL = ResourceLeak()  # borrow DTX007's derived-locals data flow


def _is_daemon_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _stored_name(ctx: ModuleContext, call: ast.Call):
    """('attr', name) for ``self.X = Thread(...)``, ('local', name) for
    ``t = Thread(...)``, (None, None) otherwise (chained/dropped)."""
    parent = ctx.parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        attr = _self_attr(t)
        if attr is not None:
            return "attr", attr
        if isinstance(t, ast.Name):
            return "local", t.id
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                return "attr", attr
    return None, None


def _daemonized(ctx: ModuleContext, fn_node: ast.AST, call: ast.Call,
                kind: Optional[str], name: Optional[str]) -> bool:
    if _is_daemon_kwarg(call):
        return True
    if name is None:
        return False
    for node in walk_function(fn_node, include_nested=True):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute) and t.attr == "daemon"
                and isinstance(node.value, ast.Constant)
                and node.value.value):
            continue
        recv = t.value
        if kind == "local" and isinstance(recv, ast.Name) \
                and recv.id == name:
            return True
        if kind == "attr" and _self_attr(recv) == name:
            return True
    return False


def _method_calls(cls_info):
    for _mname, minfo in sorted(cls_info.methods.items()):
        for node in walk_function(minfo.node, include_nested=True):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                yield minfo, node


class ThreadShutdownEvidence(Rule):
    id = "DTX012"
    name = "daemon-thread-without-shutdown-evidence"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls_name in sorted(ctx.graph.classes):
            out.extend(self._check_class(ctx, cls_name))
        return out

    # ------------------------------------------------------------ evidence
    @staticmethod
    def _event_set_somewhere(cls_info) -> bool:
        for _minfo, call in _method_calls(cls_info):
            if call.func.attr != "set":
                continue
            attr = _self_attr(call.func.value)
            if attr is not None \
                    and any(tok in attr.lower() for tok in _EVENTISH):
                return True
        return False

    @staticmethod
    def _attr_stopped(cls_info, attr: str) -> bool:
        for minfo, call in _method_calls(cls_info):
            if call.func.attr not in _STOP_METHODS:
                continue
            derived = _RL._derived_locals(minfo.node, attr)
            if _RL._mentions(call.func.value, attr, derived):
                return True
        return False

    @staticmethod
    def _escaped_attr(fn_node, name: str) -> Optional[str]:
        """Attr a local handle escapes into within the same function —
        ``self.X = t`` / ``self.X[k] = t`` / ``self.X.append(t)`` (or
        ``.add``) — so class-wide attr evidence applies to it."""
        for node in walk_function(fn_node, include_nested=True):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == name:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None and isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                    if attr is not None:
                        return attr
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "add") \
                    and any(isinstance(a, ast.Name) and a.id == name
                            for a in node.args):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    return attr
        return None

    @staticmethod
    def _local_stopped(fn_node, name: str) -> bool:
        for node in walk_function(fn_node, include_nested=True):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _STOP_METHODS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == name:
                return True
        return False

    @staticmethod
    def _started(cls_info, fn_node, kind: Optional[str],
                 name: Optional[str], call: ast.Call,
                 ctx: ModuleContext) -> bool:
        parent = ctx.parents.get(call)
        if isinstance(parent, ast.Attribute) and parent.attr == "start":
            return True  # Thread(...).start()
        if name is None:
            return False
        scopes = ([m.node for m in cls_info.methods.values()]
                  if kind == "attr" else [fn_node])
        for scope in scopes:
            for node in walk_function(scope, include_nested=True):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "start"):
                    continue
                recv = node.func.value
                if kind == "local" and isinstance(recv, ast.Name) \
                        and recv.id == name:
                    return True
                if kind == "attr" and _self_attr(recv) == name:
                    return True
        return False

    # ---------------------------------------------------------------- core
    def _check_class(self, ctx: ModuleContext, cls: str) -> List[Finding]:
        cls_info = ctx.graph.classes[cls]
        out: List[Finding] = []
        event_evidence: Optional[bool] = None  # computed lazily, once
        for mname, minfo in sorted(cls_info.methods.items()):
            for node in walk_function(minfo.node, include_nested=True):
                if not isinstance(node, ast.Call):
                    continue
                if ctx.resolve(node.func) not in _THREAD_TYPES:
                    continue
                kind, name = _stored_name(ctx, node)
                if not _daemonized(ctx, minfo.node, node, kind, name):
                    continue
                if not self._started(cls_info, minfo.node, kind, name,
                                     node, ctx):
                    continue
                if kind == "attr" and self._attr_stopped(cls_info, name):
                    continue
                if kind == "local":
                    if self._local_stopped(minfo.node, name):
                        continue
                    escaped = self._escaped_attr(minfo.node, name)
                    if escaped is not None \
                            and self._attr_stopped(cls_info, escaped):
                        continue
                if event_evidence is None:
                    event_evidence = self._event_set_somewhere(cls_info)
                if event_evidence:
                    continue
                handle = (f"self.{name}" if kind == "attr"
                          else name if kind == "local" else "the handle")
                out.append(self.finding(
                    ctx, node,
                    f"daemon thread started in {cls}.{mname}() with no "
                    f"shutdown evidence: no method joins/cancels {handle} "
                    f"and no stop-event .set() anywhere in {cls} — the "
                    "worker dies mid-operation at interpreter exit and "
                    "outlives the object in tests; give it a stop Event "
                    "its loop checks, then set+join it in close()"))
        return out
