"""DTX005: PartitionSpec / collective axis names not declared by the mesh.

Every ``PartitionSpec``/``with_sharding_constraint`` axis string — and every
``lax.psum``/``pmean``/``all_gather``/… collective's literal ``axis_name`` —
must be an axis the mesh actually declares (``parallel/mesh.py::MESH_AXES``
— dp/fsdp/tp/sp here). A typo'd or stale axis name ("data", "mdl", "x")
doesn't fail loudly: depending on context it raises deep inside GSPMD, at
trace time far from the typo, or silently falls back to replication, which
costs HBM and bandwidth instead of a traceback. Collectives drift the same
way PartitionSpecs do — a psum over a renamed axis is the same bug one
layer down. Variable axis names (e.g. ring attention's ``axis_name``
parameter, vmap-introduced axes) are out of static reach and not checked.

Declared axes come from ``[tool.dtxlint] mesh-axes`` when set, else are
extracted from ``*_AXES`` assignments of the configured ``mesh-module``.
When neither yields axis names the rule stays quiet (nothing to check
against). The mesh module itself is exempt — it's the declaration site.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Tuple

from datatunerx_tpu.analysis.config import mesh_axes_for
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

_SPEC_NAMES = (
    "jax.sharding.PartitionSpec",
    "jax.experimental.pjit.PartitionSpec",
    "jax.interpreters.pxla.PartitionSpec",
)
_CONSTRAINT_NAMES = (
    "jax.lax.with_sharding_constraint",
    "jax.experimental.pjit.with_sharding_constraint",
)
# collective → positional index of ``axis_name`` (keyword form also checked)
_COLLECTIVE_AXIS_ARG = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}


class MeshAxisDrift(Rule):
    id = "DTX005"
    name = "mesh-axis-drift"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        axes = set(mesh_axes_for(ctx.config))
        if not axes:
            return []
        mesh_module = ctx.config.resolve(ctx.config.mesh_module)
        if mesh_module and os.path.normpath(os.path.abspath(ctx.path)) \
                == os.path.normpath(os.path.abspath(mesh_module)):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved in _SPEC_NAMES:
                args = list(node.args)
                what = "PartitionSpec axes silently replicate (or crash " \
                       "in GSPMD lowering)"
            elif resolved in _CONSTRAINT_NAMES and len(node.args) >= 2:
                # direct string/tuple axis spec (P(...) args are caught by
                # the PartitionSpec branch when that call appears inline)
                args = [node.args[1]]
                what = "PartitionSpec axes silently replicate (or crash " \
                       "in GSPMD lowering)"
            elif resolved in _COLLECTIVE_AXIS_ARG:
                idx = _COLLECTIVE_AXIS_ARG[resolved]
                args = [node.args[idx]] if len(node.args) > idx else []
                args += [kw.value for kw in node.keywords
                         if kw.arg == "axis_name"]
                what = (f"{resolved.rsplit('.', 1)[-1]} over an unbound "
                        "axis fails at trace time far from the typo")
            else:
                continue
            for name, strnode in self._axis_strings(args):
                if name not in axes:
                    out.append(self.finding(
                        ctx, strnode,
                        f"axis {name!r} is not a declared mesh axis "
                        f"({', '.join(sorted(axes))}) — stale or typo'd "
                        + what))
        return out

    def _axis_strings(self, args) -> Iterable[Tuple[str, ast.AST]]:
        stack = list(args)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Tuple, ast.List)):
                stack.extend(node.elts)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                yield node.value, node
