"""DTX006: lock discipline around thread-shared attributes.
DTX007: subprocess/thread/socket created and never reaped.

Both target the gateway/prefetch bug family from PR 2/3 review: state
shared between a ``threading.Thread`` target and the public API of the
same class, and process/socket handles whose cleanup path exists but is
never reached (the ``/admin/drain`` zombie-replica leak).

DTX006 — for every class that starts a thread on one of its own methods
(``threading.Thread(target=self._worker)``), attributes the thread
context reads or writes are "shared". A PUBLIC method assigning a shared
attribute outside a ``with self.<lock>:`` block races the thread — int
stores happen to be atomic in CPython today, but compound updates and
dict/list mutations are not, and the discipline should not depend on
which kind today's diff touches. ``__init__`` and other underscore
methods are exempt (construction happens-before thread start; private
helpers are assumed called under the caller's lock).

DTX007 — a ``subprocess.Popen``/``threading.Thread``/``socket.socket``
created in a function must have a reachable disposal: a cleanup call
(terminate/kill/join/close/…) on the handle, a ``with`` block, or an
escape (returned, passed on, stored). Handles stored on ``self`` get a
class-wide check instead: SOME method of the class must dispose of
values derived from that attribute, else every instance leaks its
children. Threads marked ``daemon=True`` are exempt — they cannot block
interpreter exit, which is this rule's severity bar.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_RESOURCES = {
    "subprocess.Popen": "subprocess",
    "threading.Thread": "thread",
    "threading.Timer": "thread",
    "multiprocessing.Process": "process",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
}
_CLEANUP_METHODS = {"close", "terminate", "kill", "join", "wait",
                    "communicate", "shutdown", "stop", "cancel", "detach"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is exactly ``self.X``."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _write_targets(stmt: ast.AST) -> List[Tuple[str, ast.AST]]:
    """self-attributes written by an assignment statement: plain
    ``self.X = ...`` and container mutation ``self.X[k] = ...``."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: List[Tuple[str, ast.AST]] = []
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
            stack.extend(ast.iter_child_nodes(t))
            continue
        attr = _self_attr(t)
        if attr is not None:
            out.append((attr, t))
        elif isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                out.append((attr, t))
    return out


def _under_self_lock(ctx: ModuleContext, node: ast.AST,
                     stop: ast.AST) -> bool:
    """Is ``node`` inside a ``with self.<anything>:`` block (within the
    function ``stop``)? Any with-on-a-self-attribute counts as a lock —
    being lenient here keeps FPs down; naming doesn't matter."""
    cur = node
    parents = ctx.parents
    while cur is not stop and cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                if _self_attr(item.context_expr) is not None:
                    return True
    return False


class LockDiscipline(Rule):
    id = "DTX006"
    name = "lock-discipline"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls_name in sorted(ctx.graph.classes):
            out.extend(self._check_class(ctx, cls_name))
        return out

    def _check_class(self, ctx: ModuleContext, cls: str) -> List[Finding]:
        graph = ctx.graph
        entries = graph.thread_entry_methods(cls)
        if not entries:
            return []
        thread_ctx = graph.class_reachable(cls, entries)
        shared: Set[str] = set()
        for qualname in thread_ctx:
            info = graph.functions[qualname]
            for node in walk_function(info.node, include_nested=True):
                attr = _self_attr(node)
                if attr is not None:
                    shared.add(attr)
        # thread-started attributes like self._thread itself are lifecycle,
        # not data; they'd still be flagged if a public method reassigns
        # them unlocked, which is genuinely racy — so no exemption.
        out: List[Finding] = []
        entry_names = ", ".join(sorted(entries))
        for name, info in sorted(graph.classes[cls].methods.items()):
            if name.startswith("_") or info.qualname in thread_ctx:
                continue
            for node in walk_function(info.node, include_nested=True):
                for attr, target in _write_targets(node):
                    if attr not in shared:
                        continue
                    if _under_self_lock(ctx, target, info.node):
                        continue
                    out.append(self.finding(
                        ctx, target,
                        f"self.{attr} is used by {cls}'s background "
                        f"thread ({entry_names}) but written here in "
                        f"public {name}() without holding a lock — wrap "
                        "the write in `with self.<lock>:`"))
        return out


class ResourceLeak(Rule):
    id = "DTX007"
    name = "resource-leak"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for qualname in sorted(ctx.graph.functions):
            info = ctx.graph.functions[qualname]
            for node in walk_function(info.node):
                if not isinstance(node, ast.Call):
                    continue
                kind = _RESOURCES.get(ctx.resolve(node.func) or "")
                if kind is None:
                    continue
                if kind == "thread" and self._is_daemon(node):
                    continue
                problem = self._disposition(ctx, qualname, info, node, kind)
                if problem:
                    out.append(self.finding(ctx, node, problem))
        return out

    @staticmethod
    def _is_daemon(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False

    # --------------------------------------------------------- disposition
    def _disposition(self, ctx, qualname, info, call, kind) -> str:
        """'' when the handle is disposed/escapes; else the finding text."""
        parent = ctx.parents.get(call)
        # with Popen(...) as p: — managed
        if isinstance(parent, ast.withitem):
            return ""
        # chained immediate use: Popen(...).wait() disposes inline;
        # Thread(...).start() drops the handle
        if isinstance(parent, ast.Attribute):
            if parent.attr in _CLEANUP_METHODS:
                return ""
            return (f"{kind} handle is dropped after "
                    f"`.{parent.attr}()` — keep it and terminate/join it "
                    "on shutdown")
        if isinstance(parent, ast.Expr):
            return f"{kind} handle is created and immediately dropped"
        if isinstance(parent, ast.Assign):
            targets = parent.targets
            if len(targets) == 1:
                t = targets[0]
                if isinstance(t, ast.Name):
                    return self._check_local(ctx, info, t.id, kind)
                attr = _self_attr(t) or (
                    _self_attr(t.value) if isinstance(t, ast.Subscript)
                    else None)
                if attr is not None and info.cls is not None:
                    return self._check_class_attr(ctx, info.cls, attr, kind)
        # returned / yielded / passed as an argument / stored via other
        # shapes: the handle escapes, its owner is responsible
        return ""

    def _check_local(self, ctx, info, name: str, kind: str) -> str:
        # handle-passed-to-an-internal-callee uses: with the program graph
        # on, the callee's parameter disposition decides whether this was a
        # true handoff or a drop — recorded for the program pass, treated
        # as an escape (no module-level finding) either way
        candidates: List[dict] = []
        for node in walk_function(info.node, include_nested=True):
            if not (isinstance(node, ast.Name) and node.id == name):
                continue
            if isinstance(node.ctx, ast.Store):
                continue  # the binding (or a rebinding) itself
            parent = ctx.parents.get(node)
            if isinstance(parent, ast.Attribute):
                grand = ctx.parents.get(parent)
                if isinstance(grand, ast.Call) and grand.func is parent \
                        and parent.attr in _CLEANUP_METHODS:
                    return ""
                continue  # p.poll()/p.pid — neutral receiver use
            cand = self._call_arg_candidate(ctx, node, parent)
            if cand is not None:
                candidates.append(cand)
                continue
            # any other Load use — return, yield, `with p:`, container
            # literal, alias assignment — escapes to code we can't see;
            # its new owner is responsible
            return ""
        if candidates:
            ctx.xescape_candidates.append({
                "var": name, "kind": kind,
                "line": candidates[0]["line"], "col": candidates[0]["col"],
                "targets": candidates,
            })
            return ""
        return (f"{kind} handle `{name}` has no reachable "
                "terminate/join/close in this function and never escapes "
                "— it leaks when the function returns")

    @staticmethod
    def _call_arg_candidate(ctx, node, parent) -> Optional[dict]:
        """When ``node`` is a plain positional/keyword argument of a call
        whose callee resolves to a name, describe the pass-through:
        {callee, arg (int position or str kwarg), line, col}. None for any
        other use."""
        call, arg = None, None
        if isinstance(parent, ast.Call) and node in parent.args:
            if any(isinstance(a, ast.Starred) for a in parent.args):
                return None  # positional index unknowable
            call, arg = parent, parent.args.index(node)
        elif isinstance(parent, ast.keyword) and parent.value is node \
                and parent.arg is not None:
            grand = ctx.parents.get(parent)
            if isinstance(grand, ast.Call):
                call, arg = grand, parent.arg
        if call is None or call.func is node:
            return None
        callee = ctx.resolve(call.func)
        if not callee:
            return None
        return {"callee": callee, "arg": arg,
                "line": node.lineno, "col": node.col_offset}

    def _check_class_attr(self, ctx, cls: str, attr: str, kind: str) -> str:
        graph = ctx.graph
        for name, minfo in graph.classes[cls].methods.items():
            derived = self._derived_locals(minfo.node, attr)
            for node in walk_function(minfo.node, include_nested=True):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _CLEANUP_METHODS \
                        and self._mentions(node.func.value, attr, derived):
                    return ""
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if self._mentions(item.context_expr, attr, derived):
                            return ""
        return (f"{kind} handle stored in self.{attr} but no method of "
                f"{cls} ever terminates/joins/closes values from "
                f"self.{attr} — each instance leaks its children "
                "(the /admin/drain zombie shape)")

    def _derived_locals(self, fn_node, attr: str) -> Set[str]:
        """Local names whose value derives from self.<attr> (two data-flow
        hops: covers `procs = list(self._procs.values())` then
        `for p in procs:`)."""
        derived: Set[str] = set()
        for _ in range(2):
            for node in walk_function(fn_node, include_nested=True):
                value, targets = None, []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    value, targets = node.iter, [node.target]
                if value is None or not self._mentions(value, attr, derived):
                    continue
                stack = list(targets)
                while stack:
                    t = stack.pop()
                    if isinstance(t, ast.Name):
                        derived.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
                        stack.extend(ast.iter_child_nodes(t))
        return derived

    @staticmethod
    def _mentions(expr: ast.AST, attr: str, derived: Set[str]) -> bool:
        for node in ast.walk(expr):
            if _self_attr(node) == attr:
                return True
            if isinstance(node, ast.Name) and node.id in derived:
                return True
        return False


def param_disposition(ctx, fn_node, pname: str) -> str:
    """What a function does with one of its parameters, for the program
    pass's cross-module escape analysis:

      * ``disposes`` — a cleanup method is called on it (or ``with p:``);
      * ``escapes``  — returned/stored/passed on: someone else owns it;
      * ``drops``    — only neutral receiver uses (or none): a resource
        handle passed here dies with the frame, so the CALLER still leaks.
    """
    for node in walk_function(fn_node, include_nested=True):
        if not (isinstance(node, ast.Name) and node.id == pname):
            continue
        if isinstance(node.ctx, ast.Store):
            return "escapes"  # rebound: can't track further, be safe
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Attribute):
            grand = ctx.parents.get(parent)
            if isinstance(grand, ast.Call) and grand.func is parent \
                    and parent.attr in _CLEANUP_METHODS:
                return "disposes"
            continue  # p.poll()/p.pid — neutral receiver use
        if isinstance(parent, ast.withitem) and parent.context_expr is node:
            return "disposes"
        return "escapes"
    return "drops"
