"""DTX003: Python control flow on traced values inside jitted functions.

``if jnp.any(mask):`` inside a ``@jax.jit`` function calls ``bool()`` on a
tracer — a TracerBoolConversionError at trace time in the best case, and
in the worst (shape-dependent code that happens to trace) a silently
baked-in branch that ignores runtime values. The fix is ``jax.lax.cond``
/ ``jax.lax.while_loop`` or ``jnp.where``.

Detection: a function is "jitted" when decorated with ``jax.jit`` (bare,
called, or via ``functools.partial(jax.jit, ...)``), or when the module
wraps it by name — ``g = jax.jit(f)``. Inside such functions, an
``if``/``while`` whose TEST contains a ``jnp.*``/``jax.lax.*``/
``jax.nn.*`` CALL is flagged. Attribute-only tests (``x.ndim``,
``x.shape[0]``, ``x.dtype``) are static under tracing and stay allowed.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")
_TRACED_CALL_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.scipy.")


def _is_jit_expr(ctx: ModuleContext, node: ast.AST) -> bool:
    """True for ``jax.jit``, ``jax.jit(...)``, ``partial(jax.jit, ...)``."""
    if ctx.resolve(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        if ctx.resolve(node.func) in _JIT_NAMES:
            return True
        if ctx.resolve(node.func) == "functools.partial" and node.args \
                and ctx.resolve(node.args[0]) in _JIT_NAMES:
            return True
    return False


class TracerControlFlow(Rule):
    id = "DTX003"
    name = "tracer-control-flow"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for qualname in sorted(self._jitted(ctx)):
            info = ctx.graph.functions[qualname]
            for node in walk_function(info.node, include_nested=True):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                traced = self._traced_call_in(ctx, node.test)
                if traced:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(self.finding(
                        ctx, node,
                        f"Python `{kind}` on the traced value "
                        f"{traced}(...) inside jitted {qualname}: use "
                        "jax.lax.cond/while_loop or jnp.where — a tracer "
                        "has no stable truth value"))
        return out

    def _jitted(self, ctx: ModuleContext) -> Set[str]:
        jitted: Set[str] = set()
        for qualname, info in ctx.graph.functions.items():
            for dec in getattr(info.node, "decorator_list", []):
                if _is_jit_expr(ctx, dec):
                    jitted.add(qualname)
        # g = jax.jit(f) / self._fn = jax.jit(self._impl)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.resolve(node.func) in _JIT_NAMES and node.args):
                continue
            target = node.args[0]
            if isinstance(target, ast.Name):
                for cand in (target.id,):
                    jitted.update(q for q, i in ctx.graph.functions.items()
                                  if i.name == cand and i.cls is None)
            elif isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self":
                jitted.update(q for q, i in ctx.graph.functions.items()
                              if i.name == target.attr and i.cls is not None)
        return {q for q in jitted if q in ctx.graph.functions}

    def _traced_call_in(self, ctx: ModuleContext, test: ast.AST) -> str:
        for node in ast.walk(test):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func)
                if resolved and any(resolved.startswith(p)
                                    for p in _TRACED_CALL_PREFIXES):
                    return resolved
        return ""
