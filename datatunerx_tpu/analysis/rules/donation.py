"""DTX010: donated-buffer reuse — reading a variable after passing it to a
``donate_argnums`` call.

``jax.jit(step, donate_argnums=(0,))`` tells XLA it may alias the donated
operand's buffer for an output: after ``new = step(state, batch)`` the
old ``state`` is DELETED on TPU (reads raise) and silently ALIASED on
CPU — the worst kind of platform-dependent bug, because the CPU test
suite passes while the TPU run corrupts or crashes. This repo's serving
plane donates the KV cache through every decode step, so the shape is
one refactor away at all times.

Detection, per function scope:
  * donated callables: ``g = jax.jit(f, donate_argnums=…)`` at module or
    local level (also ``donate_argnames``), and direct
    ``jax.jit(f, donate_argnums=…)(args)`` calls;
  * at each call of one, map the donated positions/names to plain-Name
    arguments;
  * flag any LOAD of that name after the call statement — unless the
    call's own statement rebinds the name (``state = step(state, b)``,
    the loop-carry idiom, which is exactly how donation is meant to be
    used) or the name is rebound before the use by a store that
    DOMINATES it (a conditional rebind — ``if err: state = reset()`` —
    does not clear the un-rebound path, which still reads the donated
    buffer).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# statement-list fields whose execution is conditional on control flow;
# `finally` bodies always run and are deliberately absent
_COND_ARMS = {
    ast.If: ("body", "orelse"),
    ast.While: ("body", "orelse"),
    ast.For: ("body", "orelse"),
    ast.AsyncFor: ("body", "orelse"),
    ast.Try: ("body", "handlers", "orelse"),
    ast.ExceptHandler: ("body",),
}


def _branch_paths(fn_node: ast.AST) -> Dict[int, Tuple]:
    """id(node) → tuple of (construct id, arm field) conditional arms
    enclosing it within ``fn_node``. A store dominates a load iff the
    store's path is a prefix of the load's — same or enclosing arm."""
    paths: Dict[int, Tuple] = {id(fn_node): ()}

    def visit(node: ast.AST, path: Tuple):
        cond_fields = _COND_ARMS.get(type(node), ())
        for field, value in ast.iter_fields(node):
            arm = path + ((id(node), field),) if field in cond_fields \
                else path
            children = value if isinstance(value, list) else [value]
            for child in children:
                if not isinstance(child, ast.AST):
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # separate frame; walk_function skips it too
                paths[id(child)] = arm
                visit(child, arm)

    visit(fn_node, ())
    return paths


def donated_spec(ctx: ModuleContext,
                 call: ast.Call) -> Optional[Tuple[Tuple[int, ...],
                                                   Tuple[str, ...]]]:
    """(donated positions, donated kwarg names) when ``call`` is a
    jit-with-donation, else None."""
    if ctx.resolve(call.func) not in _JIT_NAMES:
        return None
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, int):
                        nums.append(elt.value)
        elif kw.arg == "donate_argnames":
            v = kw.value
            vals = [v] if isinstance(v, ast.Constant) else \
                list(v.elts) if isinstance(v, (ast.Tuple, ast.List)) else []
            for elt in vals:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.append(elt.value)
    if not nums and not names:
        return None
    return tuple(nums), tuple(names)


def _assigned_names(stmt: ast.AST) -> Set[str]:
    out: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
            stack.extend(ast.iter_child_nodes(t))
    return out


class DonatedBufferReuse(Rule):
    id = "DTX010"
    name = "donated-buffer-reuse"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        module_donated = self._donated_bindings(ctx, ctx.tree.body)
        for qualname in sorted(ctx.graph.functions):
            info = ctx.graph.functions[qualname]
            donated = dict(module_donated)
            donated.update(self._donated_bindings(ctx, info.node.body))
            out.extend(self._check_function(ctx, info.node, donated))
        return out

    def _donated_bindings(
            self, ctx: ModuleContext,
            body: Sequence[ast.stmt]) -> Dict[str, Tuple[Tuple[int, ...],
                                                         Tuple[str, ...]]]:
        """name → donation spec for ``g = jax.jit(..., donate_argnums=…)``
        assignments directly in ``body`` (no nested descent: inner scopes
        collect their own)."""
        out: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}
        for stmt in body:
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)):
                continue
            spec = donated_spec(ctx, stmt.value)
            if spec is None:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = spec
        return out

    def _check_function(self, ctx: ModuleContext, fn_node: ast.AST,
                        donated) -> List[Finding]:
        out: List[Finding] = []
        # gather calls of donated callables (by name, or direct jit(...)())
        for node in walk_function(fn_node):
            if not isinstance(node, ast.Call):
                continue
            spec = None
            shown = ""
            if isinstance(node.func, ast.Name) and node.func.id in donated:
                spec = donated[node.func.id]
                shown = node.func.id
            elif isinstance(node.func, ast.Call):
                spec = donated_spec(ctx, node.func)
                shown = "jax.jit(...)"
            if spec is None:
                continue
            nums, names = spec
            victims: List[Tuple[str, ast.Name]] = []
            for i in nums:
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    victims.append((node.args[i].id, node.args[i]))
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value, ast.Name):
                    victims.append((kw.value.id, kw.value))
            if victims:
                out.extend(self._reads_after(ctx, fn_node, node, shown,
                                             victims))
        return out

    def _reads_after(self, ctx: ModuleContext, fn_node: ast.AST,
                     call: ast.Call, shown: str,
                     victims: List[Tuple[str, ast.Name]]) -> List[Finding]:
        stmt = self._enclosing_stmt(ctx, call)
        rebound_here = _assigned_names(stmt) if stmt is not None else set()
        end = getattr(stmt, "end_lineno", call.lineno) if stmt is not None \
            else call.lineno
        loop = self._enclosing_loop(ctx, stmt, fn_node)
        out: List[Finding] = []
        for name, arg_node in victims:
            if name in rebound_here:
                continue  # state = step(state, …): the donation idiom
            use = self._first_read_after(fn_node, name, end)
            if use is None and loop is not None \
                    and not self._stored_in(loop, name):
                # the loop back-edge: nothing in the loop rebinds the
                # victim, so iteration N+1's call argument reads the
                # buffer iteration N donated
                use = arg_node
            if use is not None:
                out.append(self.finding(
                    ctx, use,
                    f"`{name}` was donated to {shown}() "
                    "(donate_argnums) and is read afterwards — the "
                    "buffer is deleted on TPU after the call (and "
                    "silently aliased on CPU); use the returned value "
                    "or drop the donation"))
        return out

    @staticmethod
    def _enclosing_loop(ctx: ModuleContext, stmt: Optional[ast.AST],
                        fn_node: ast.AST) -> Optional[ast.AST]:
        cur = stmt
        while cur is not None and cur is not fn_node:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return cur
            cur = ctx.parents.get(cur)
        return None

    @staticmethod
    def _stored_in(scope: ast.AST, name: str) -> bool:
        """Any Store of ``name`` within ``scope``, nested defs excluded
        (they run on their own frame and bind their own scope)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Name) and node.id == name \
                    and isinstance(node.ctx, ast.Store):
                return True
            stack.extend(ast.iter_child_nodes(node))
        return False

    def _enclosing_stmt(self, ctx: ModuleContext,
                        node: ast.AST) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = ctx.parents.get(cur)
        return cur

    def _first_read_after(self, fn_node: ast.AST, name: str,
                          after_line: int) -> Optional[ast.Name]:
        """First Load of ``name`` after ``after_line`` that is not preceded
        by a DOMINATING rebinding — a store whose branch path is a prefix
        of the load's. ``if err: state = reset()`` only clears reads on
        the ``err`` path; the fall-through still reads the donated buffer."""
        paths = _branch_paths(fn_node)
        events: List[Tuple[int, str, ast.AST]] = []
        for node in walk_function(fn_node):
            if isinstance(node, ast.Name) and node.id == name:
                kind = "store" if isinstance(node.ctx, ast.Store) else "load"
                events.append((node.lineno, kind, node))
        events.sort(key=lambda e: e[0])
        stores: List[Tuple] = []
        for line, kind, node in events:
            if line <= after_line:
                continue
            p = paths.get(id(node), ())
            if kind == "store":
                stores.append(p)
                continue
            if any(p[:len(sp)] == sp for sp in stores):
                continue  # every path to this read rebound the name
            return node
        return None
