"""DTX004: the same PRNG key consumed by two jax.random calls.

JAX keys are values, not stateful generators: passing one key to two
consumers (``normal(key, ...)`` then ``uniform(key, ...)``) silently
correlates the two draws — every consumption should go through its own
``split``/``fold_in`` product. ``fold_in(key, i)`` itself may take the
same base key any number of times (the distinct-stream idiom); ``split``
may not — two bare ``split(key)`` calls return identical children.
The rule tracks each local name consumed
by a ``jax.random.*`` call (as first positional arg or ``key=``) in
statement order and flags:

  * a second consumption of the same name with no reassignment between
    (mutually exclusive if/else branches are NOT double consumption and
    stay allowed);
  * a consumption inside a loop whose key was last assigned OUTSIDE the
    loop — every iteration reuses the same key (the loop-carry idiom
    ``key, sub = jax.random.split(key)`` is recognized and allowed).

Heuristic and intra-function only — it cannot see a key escaping through
a call — but this is exactly the shape key-reuse bugs take in practice.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

# fold_in is deliberately non-consuming: deriving per-step/per-layer
# streams as fold_in(base_key, i) with distinct data REQUIRES passing the
# same base key repeatedly — that's the documented idiom, not reuse.
# (Statically we can't prove the fold data differs; flagging the idiom
# would bury real findings under suppressions.)
_NON_CONSUMING = {"PRNGKey", "key", "fold_in", "wrap_key_data", "key_data",
                  "key_impl", "default_prng_impl"}
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

IfPath = Tuple[Tuple[int, str], ...]
LoopPath = Tuple[int, ...]


def _compatible(a: IfPath, b: IfPath) -> bool:
    """Two branch paths can execute in the same run unless they take
    different arms of the same ``if``."""
    arms_a = dict(a)
    for if_id, arm in b:
        if if_id in arms_a and arms_a[if_id] != arm:
            return False
    return True


class _Event:
    __slots__ = ("kind", "name", "if_path", "loop_path", "node", "carry")

    def __init__(self, kind, name, if_path, loop_path, node, carry=False):
        self.kind = kind  # "use" | "assign"
        self.name = name
        self.if_path = if_path
        self.loop_path = loop_path
        self.node = node
        self.carry = carry  # use feeding a reassignment of the same name


class PRNGKeyReuse(Rule):
    id = "DTX004"
    name = "prng-key-reuse"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for qualname in sorted(ctx.graph.functions):
            info = ctx.graph.functions[qualname]
            events: List[_Event] = []
            for arg in self._params(info.node):
                events.append(_Event("assign", arg, (), (), info.node))
            self._scan(ctx, info.node.body, (), (), events)
            out.extend(self._analyze(ctx, events))
        return out

    @staticmethod
    def _params(fn) -> List[str]:
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                names.append(extra.arg)
        return names

    # ------------------------------------------------------------- events
    def _scan(self, ctx, stmts, if_path: IfPath, loop_path: LoopPath,
              events: List[_Event]):
        for stmt in stmts:
            if isinstance(stmt, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
                continue  # separate scope; analyzed as its own function
            if isinstance(stmt, ast.If):
                self._uses(ctx, stmt.test, if_path, loop_path, events)
                self._scan(ctx, stmt.body,
                           if_path + ((id(stmt), "body"),), loop_path, events)
                self._scan(ctx, stmt.orelse,
                           if_path + ((id(stmt), "orelse"),), loop_path,
                           events)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._uses(ctx, stmt.iter, if_path, loop_path, events)
                self._assigns(stmt.target, if_path,
                              loop_path + (id(stmt),), events)
                self._scan(ctx, stmt.body, if_path,
                           loop_path + (id(stmt),), events)
                self._scan(ctx, stmt.orelse, if_path, loop_path, events)
            elif isinstance(stmt, ast.While):
                inner = loop_path + (id(stmt),)
                self._uses(ctx, stmt.test, if_path, inner, events)
                self._scan(ctx, stmt.body, if_path, inner, events)
                self._scan(ctx, stmt.orelse, if_path, loop_path, events)
            elif isinstance(stmt, ast.Try):
                self._scan(ctx, stmt.body, if_path, loop_path, events)
                for handler in stmt.handlers:
                    self._scan(ctx, handler.body, if_path, loop_path, events)
                self._scan(ctx, stmt.orelse, if_path, loop_path, events)
                self._scan(ctx, stmt.finalbody, if_path, loop_path, events)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._uses(ctx, item.context_expr, if_path, loop_path,
                               events)
                    if item.optional_vars is not None:
                        self._assigns(item.optional_vars, if_path, loop_path,
                                      events)
                self._scan(ctx, stmt.body, if_path, loop_path, events)
            elif isinstance(stmt, ast.Assign):
                targets = self._target_names(stmt.targets)
                self._uses(ctx, stmt.value, if_path, loop_path, events,
                           carry_names=targets)
                for t in stmt.targets:
                    self._assigns(t, if_path, loop_path, events)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    targets = self._target_names([stmt.target])
                    self._uses(ctx, stmt.value, if_path, loop_path, events,
                               carry_names=targets)
                self._assigns(stmt.target, if_path, loop_path, events)
            else:
                for child in ast.iter_child_nodes(stmt):
                    self._uses(ctx, child, if_path, loop_path, events)

    def _target_names(self, targets) -> Set[str]:
        names: Set[str] = set()
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
                stack.extend(ast.iter_child_nodes(t))
        return names

    def _uses(self, ctx, expr, if_path, loop_path, events,
              carry_names: Optional[Set[str]] = None):
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if not resolved or not resolved.startswith("jax.random."):
                continue
            fn = resolved.rsplit(".", 1)[1]
            if fn in _NON_CONSUMING:
                continue
            key_arg = None
            if node.args and isinstance(node.args[0], ast.Name):
                key_arg = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "key" and isinstance(kw.value, ast.Name):
                        key_arg = kw.value
            if key_arg is None:
                continue
            carry = bool(carry_names) and key_arg.id in carry_names
            events.append(_Event("use", key_arg.id, if_path, loop_path,
                                 node, carry=carry))

    def _assigns(self, target, if_path, loop_path, events):
        for name in sorted(self._target_names([target])):
            events.append(_Event("assign", name, if_path, loop_path, target))

    # ----------------------------------------------------------- analysis
    def _analyze(self, ctx, events: List[_Event]) -> List[Finding]:
        out: List[Finding] = []
        last_assign: Dict[str, _Event] = {}
        uses_since: Dict[str, List[_Event]] = {}
        for e in events:
            if e.kind == "assign":
                last_assign[e.name] = e
                uses_since[e.name] = []
                continue
            prior = [u for u in uses_since.setdefault(e.name, [])
                     if _compatible(u.if_path, e.if_path)]
            if prior:
                out.append(self.finding(
                    ctx, e.node,
                    f"PRNG key `{e.name}` already consumed at line "
                    f"{prior[0].node.lineno} — every consumer needs its "
                    "own key from jax.random.split/fold_in"))
            elif not e.carry:
                la = last_assign.get(e.name)
                assigned_loops = set(la.loop_path) if la is not None else set()
                if any(lp not in assigned_loops for lp in e.loop_path):
                    out.append(self.finding(
                        ctx, e.node,
                        f"PRNG key `{e.name}` consumed inside a loop but "
                        "assigned outside it — every iteration draws with "
                        "the SAME key; split or fold_in per iteration"))
            uses_since[e.name].append(e)
        return out
