"""dtxlint rule registry. Each rule is a self-contained visitor class;
adding one = write the class, import it here, add a fixture pair to
tests/test_dtxlint.py (see README "Static analysis")."""

from typing import List, Sequence

from datatunerx_tpu.analysis.core import Rule
from datatunerx_tpu.analysis.rules.blocking import BlockingUnderLock
from datatunerx_tpu.analysis.rules.concurrency import LockDiscipline, ResourceLeak
from datatunerx_tpu.analysis.rules.donation import DonatedBufferReuse
from datatunerx_tpu.analysis.rules.host_sync import HostSyncInHotPath
from datatunerx_tpu.analysis.rules.lockorder import LockOrderInversion
from datatunerx_tpu.analysis.rules.prng import PRNGKeyReuse
from datatunerx_tpu.analysis.rules.retrace import JitInLoop, ModuleImportDeviceWork
from datatunerx_tpu.analysis.rules.sharding import MeshAxisDrift
from datatunerx_tpu.analysis.rules.thread_shutdown import ThreadShutdownEvidence
from datatunerx_tpu.analysis.rules.tracer import TracerControlFlow

RULE_CLASSES = (
    HostSyncInHotPath,    # DTX001
    JitInLoop,            # DTX002
    TracerControlFlow,    # DTX003
    PRNGKeyReuse,         # DTX004
    MeshAxisDrift,        # DTX005
    LockDiscipline,       # DTX006
    ResourceLeak,         # DTX007
    ModuleImportDeviceWork,  # DTX008
    BlockingUnderLock,    # DTX009
    DonatedBufferReuse,   # DTX010
    LockOrderInversion,   # DTX011
    ThreadShutdownEvidence,  # DTX012
)


def all_rules() -> List[Rule]:
    return [cls() for cls in RULE_CLASSES]


def rules_by_id(ids: Sequence[str]) -> List[Rule]:
    wanted = set(ids)
    return [cls() for cls in RULE_CLASSES if cls.id in wanted]
