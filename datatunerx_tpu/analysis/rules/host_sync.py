"""DTX001: host-synchronizing calls inside hot-path functions.

The bug class PR 3 removed by hand: a ``float(loss)`` / ``.item()`` /
``np.asarray(x)`` / ``jax.device_get`` / ``.block_until_ready()`` inside
the step loop blocks the host on the device stream every step, draining
the dispatch pipeline — silent, and worth double-digit % of step time.

"Hot path" = any function whose bare name matches a configured
``hot-functions`` pattern, plus everything reachable from one through the
intra-module call graph (call, reference, and nesting edges).

Not flagged: ``float()``/``int()`` of plain constants (unit conversion,
argument parsing) — only conversions of computed values can sync.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

# dotted names that force a device→host transfer / stream sync
_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.block_until_ready": "jax.block_until_ready",
}
# method names with the same effect regardless of receiver
_SYNC_METHODS = {"item", "block_until_ready", "tolist"}


class HostSyncInHotPath(Rule):
    id = "DTX001"
    name = "host-sync-in-hot-path"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        hot = ctx.graph.reachable(tuple(ctx.config.hot_functions))
        for qualname in sorted(hot):
            info = ctx.graph.functions[qualname]
            for node in walk_function(info.node):
                if not isinstance(node, ast.Call):
                    continue
                label = self._sync_label(ctx, node)
                if label:
                    out.append(self.finding(
                        ctx, node,
                        f"{label} in hot path "
                        f"({qualname} is reachable from a hot function); "
                        "this blocks the host on the device stream every "
                        "step — move it behind a logging boundary or use "
                        "MetricsBuffer"))
        return out

    def _sync_label(self, ctx: ModuleContext, node: ast.Call) -> str:
        func = node.func
        # float(x)/int(x) of a computed value
        if isinstance(func, ast.Name) and func.id in ("float", "int"):
            if node.args and not isinstance(node.args[0], ast.Constant):
                return f"{func.id}() on a device value"
            return ""
        resolved = ctx.resolve(func)
        if resolved in _SYNC_CALLS:
            return f"{_SYNC_CALLS[resolved]}()"
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
            return f".{func.attr}()"
        return ""
