"""DTX001: host-synchronizing calls inside hot-path functions.

The bug class PR 3 removed by hand: a ``float(loss)`` / ``.item()`` /
``np.asarray(x)`` / ``jax.device_get`` / ``.block_until_ready()`` inside
the step loop blocks the host on the device stream every step, draining
the dispatch pipeline — silent, and worth double-digit % of step time.

"Hot path" = any function whose bare name matches a configured
``hot-functions`` pattern, plus any code inside a ``# dtxlint: hot-begin``
/ ``# dtxlint: hot-end`` region, plus everything reachable from either
through the call graph (call, reference, and nesting edges). With the
program graph on (the default for ``dtx lint``), reachability crosses
module boundaries — this per-module rule is then replaced by the
program-level pass in ``analysis/program.py``, which reuses the helpers
here.

Not flagged: ``float()``/``int()`` of plain constants (unit conversion,
argument parsing) — only conversions of computed values can sync.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from datatunerx_tpu.analysis.callgraph import walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

# dotted names that force a device→host transfer / stream sync
_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.block_until_ready": "jax.block_until_ready",
}
# method names with the same effect regardless of receiver
_SYNC_METHODS = {"item", "block_until_ready", "tolist"}


def sync_label(ctx: ModuleContext, node: ast.Call) -> str:
    """Human label when ``node`` is a host-sync call, else ''. Shared by
    the per-module rule, the program-level pass, and DTX009."""
    func = node.func
    # float(x)/int(x) of a computed value
    if isinstance(func, ast.Name) and func.id in ("float", "int"):
        if node.args and not isinstance(node.args[0], ast.Constant):
            return f"{func.id}() on a device value"
        return ""
    resolved = ctx.resolve(func)
    if resolved in _SYNC_CALLS:
        return f"{_SYNC_CALLS[resolved]}()"
    if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
        return f".{func.attr}()"
    return ""


def hot_roots(ctx: ModuleContext) -> Set[str]:
    """Module-local hot roots: functions matching a hot-functions pattern,
    functions DEFINED inside a hot region, and local targets of calls made
    from inside a hot region (at module import time or within any
    function)."""
    graph = ctx.graph
    roots = set(graph.reachable(tuple(ctx.config.hot_functions)))
    if not ctx.hot_regions:
        return roots
    for qualname, info in graph.functions.items():
        if ctx.in_hot_region(info.lineno):
            roots.add(qualname)
    for caller, sites in graph.edge_sites.items():
        for target, line in sites:
            if ctx.in_hot_region(line):
                roots.add(target)
    for target, line in graph.module_sites:
        if ctx.in_hot_region(line):
            roots.add(target)
    return roots


def region_sync_findings(rule: Rule, ctx: ModuleContext,
                         hot: Set[str]) -> List[Tuple[ast.Call, str, str]]:
    """(call node, label, where) for sync calls lexically inside a hot
    region but NOT already covered by a hot function in ``hot`` — so a
    marked step loop inside an otherwise-cold ``main`` still flags."""
    out: List[Tuple[ast.Call, str, str]] = []
    if not ctx.hot_regions:
        return out
    covered_spans = []
    for qualname in hot:
        info = ctx.graph.functions.get(qualname)
        if info is not None:
            covered_spans.append(
                (info.lineno, getattr(info.node, "end_lineno", info.lineno),
                 qualname))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not ctx.in_hot_region(node.lineno):
            continue
        if any(s <= node.lineno <= e for s, e, _ in covered_spans):
            continue
        label = sync_label(ctx, node)
        if label:
            out.append((node, label, "a `# dtxlint: hot-begin` region"))
    return out


class HostSyncInHotPath(Rule):
    id = "DTX001"
    name = "host-sync-in-hot-path"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        hot = ctx.graph.reachable_from(hot_roots(ctx))
        for qualname in sorted(hot):
            info = ctx.graph.functions[qualname]
            for node in walk_function(info.node):
                if not isinstance(node, ast.Call):
                    continue
                label = sync_label(ctx, node)
                if label:
                    out.append(self.finding(
                        ctx, node,
                        f"{label} in hot path "
                        f"({qualname} is reachable from a hot function); "
                        "this blocks the host on the device stream every "
                        "step — move it behind a logging boundary or use "
                        "MetricsBuffer"))
        for node, label, where in region_sync_findings(self, ctx, hot):
            out.append(self.finding(
                ctx, node,
                f"{label} in hot path (inside {where}); this blocks the "
                "host on the device stream every step — move it behind a "
                "logging boundary or use MetricsBuffer"))
        return out
