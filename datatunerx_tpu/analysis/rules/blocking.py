"""DTX009: blocking calls inside a lock-guarded `with` body.

The gateway-stall shape we keep hand-auditing: the replica pool, engine
scheduler, and prefetchers all serialize state behind ``with self._lock:``
— a device sync, ``subprocess`` wait, ``requests``/socket I/O, an
unbounded ``queue.get()``, or a bare ``time.sleep`` inside that body
holds the lock across an operation with no latency bound, and every
other thread (including the request path) convoys behind it. PR 4's
drain-leak and PR 5's shutdown-flag race both lived one line away from
exactly this.

A "lock" is a ``with`` context whose expression is ``self.<attr>`` or a
bare/module-level name containing ``lock``/``mutex``/``cond``/``sem``
(case-insensitive) — naming-based on purpose: ``with self._session:`` is
not a lock and must not flag.

Blocking calls (direct):
  * device sync — the explicit DTX001 set (``np.asarray``, ``.item()``,
    ``jax.device_get``, ``.block_until_ready()``); the ``float()``-of-a-
    computed-value heuristic stays DTX001-only (under a lock it would
    flag ordinary parsing);
  * ``subprocess.run/call/check_call/check_output`` and no-timeout
    ``.wait()`` / ``.communicate()`` / ``.join()`` on any receiver
    (``proc.wait(timeout=10)`` and ``event.wait(interval)`` are bounded
    and exempt);
  * ``requests.*`` / ``urllib.request.urlopen`` / ``socket.create_
    connection`` and socket-ish ``.recv/.accept/.connect/.sendall``;
  * ``.get()`` with no positional args and no finite ``timeout=`` (the
    ``queue.get(timeout=None)`` shape; ``d.get(key)`` has args and is
    exempt);
  * ``time.sleep``.

With the program graph on, the pass in ``analysis/program.py`` extends
this transitively: a call under a lock to a function whose reachable
closure contains one of the sites above is flagged at the call site.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from datatunerx_tpu.analysis.callgraph import resolve_name, walk_function
from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule
from datatunerx_tpu.analysis.rules.host_sync import sync_label

_LOCKISH = ("lock", "mutex", "cond", "sem")

_BLOCKING_EXACT = {
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "urllib.request.urlopen": "urllib.request.urlopen()",
    "socket.create_connection": "socket.create_connection()",
    "time.sleep": "time.sleep()",
}
_BLOCKING_PREFIXES = ("requests.",)
_BLOCKING_METHODS = {"recv", "recvfrom", "accept", "connect", "sendall"}
# blocking only without a bound: a positional arg or finite timeout= is a
# latency cap (proc.wait(timeout=10), event.wait(interval), t.join(5))
_BOUNDABLE_METHODS = {"wait", "communicate", "join"}


def _lockish_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _LOCKISH)


def lock_name(item_expr: ast.AST) -> Optional[str]:
    """Rendered lock name when a with-item expression looks like a lock
    (``self._lock``, ``_POOL_LOCK``, ``cls._cv``), else None."""
    if isinstance(item_expr, ast.Attribute) and _lockish_name(item_expr.attr):
        if isinstance(item_expr.value, ast.Name):
            return f"{item_expr.value.id}.{item_expr.attr}"
        return item_expr.attr
    if isinstance(item_expr, ast.Name) and _lockish_name(item_expr.id):
        return item_expr.id
    return None


def _no_finite_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return isinstance(kw.value, ast.Constant) and kw.value.value is None
    return True


def blocking_label(ctx: ModuleContext, node: ast.Call) -> str:
    """Human label when ``node`` is a blocking call, else ''."""
    sync = sync_label(ctx, node)
    if sync and not sync.endswith("on a device value"):
        # the float()/int() heuristic is DTX001's: under a lock it would
        # flag ordinary string/number parsing, so only explicit syncs count
        return f"device sync {sync}"
    resolved = ctx.resolve(node.func)
    if resolved in _BLOCKING_EXACT:
        return _BLOCKING_EXACT[resolved]
    if resolved and any(resolved.startswith(p) for p in _BLOCKING_PREFIXES):
        return f"{resolved}()"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in _BLOCKING_METHODS:
            return f".{attr}()"
        if attr in _BOUNDABLE_METHODS and not node.args \
                and _no_finite_timeout(node):
            return f".{attr}() without timeout"
        if attr == "get" and not node.args and _no_finite_timeout(node):
            for kw in node.keywords:
                if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                        and not kw.value.value:
                    return ""
            return ".get() without timeout"
    return ""


def locked_regions(fn_node: ast.AST) -> List[Tuple[ast.AST, str]]:
    """(with-node, lock name) for every lock-guarded with in one function."""
    out: List[Tuple[ast.AST, str]] = []
    for node in walk_function(fn_node, include_nested=True):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = lock_name(item.context_expr)
                if name:
                    out.append((node, name))
                    break
    return out


def calls_under_lock(ctx: ModuleContext,
                     fn_node: ast.AST) -> List[Tuple[ast.Call, str]]:
    """(call, lock name) for calls lexically inside a lock-guarded with
    body (the with-item expressions themselves are outside)."""
    out: List[Tuple[ast.Call, str]] = []
    for with_node, name in locked_regions(fn_node):
        body_stack: List[ast.AST] = list(with_node.body)
        while body_stack:
            node = body_stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested def runs later, maybe without the lock
            body_stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                out.append((node, name))
    return out


class BlockingUnderLock(Rule):
    id = "DTX009"
    name = "blocking-call-under-lock"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        seen = set()
        for qualname in sorted(ctx.graph.functions):
            info = ctx.graph.functions[qualname]
            for call, lock in calls_under_lock(ctx, info.node):
                key = (call.lineno, call.col_offset)
                if key in seen:
                    continue  # nested locks: report once, innermost lock
                seen.add(key)
                label = blocking_label(ctx, call)
                if label:
                    out.append(self.finding(
                        ctx, call,
                        f"{label} while holding {lock}: every thread "
                        "contending on the lock convoys behind an "
                        "unbounded operation — move it outside the "
                        "critical section or add a timeout"))
        return out
