"""DTX002: jit-in-loop / unstable static args. DTX008: device work at import.

DTX002 — ``jax.jit`` evaluated inside a ``for``/``while`` body builds a
fresh wrapper (empty compile cache) per iteration: a retrace/recompile
storm that looks like "TPU slow" rather than an error. Also flagged:
``static_argnums``/``static_argnames`` given a set/dict/comprehension —
non-hashable or iteration-order-unstable values that either fail at trace
time or silently change the cache key between runs.

DTX008 — ``jnp.*`` / ``jax.random.*`` / ``jax.devices()`` / ``jax.
device_put`` executed at module top level (module body, class body, or a
function's DEFAULT ARGUMENT) runs device work at import: it initializes
the backend before the program can pick platforms/meshes (breaks
JAX_PLATFORMS selection and multi-process init) and allocates on
whichever device import happened to land on. Hoist into a function or
compute lazily. ``jax.jit(fn)`` at module level is fine — building a
wrapper is host-only and idiomatic; dtype/constant attributes
(``jnp.float32``, ``jnp.pi``) are data, not work.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from datatunerx_tpu.analysis.core import Finding, ModuleContext, Rule

_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")
_UNSTABLE_STATIC = (ast.Dict, ast.Set, ast.DictComp, ast.SetComp,
                    ast.ListComp, ast.GeneratorExp)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class _LoopVisitor(ast.NodeVisitor):
    """Tracks loop depth; function scopes reset it (their bodies run when
    called, not where defined), but decorators and default args evaluate
    in the enclosing scope and keep the current depth."""

    def __init__(self, rule: "JitInLoop", ctx: ModuleContext):
        self.rule = rule
        self.ctx = ctx
        self.out: List[Finding] = []
        self.depth = 0

    def _visit_loop(self, node):
        for header in ("iter", "test"):  # evaluated once, outside the body
            expr = getattr(node, header, None)
            if expr is not None:
                self.visit(expr)
        self.depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.depth -= 1

    visit_For = visit_AsyncFor = visit_While = _visit_loop

    def _visit_scope(self, node):
        for dec in getattr(node, "decorator_list", []):
            self.visit(dec)
        if isinstance(node, _FUNC_NODES):
            for default in node.args.defaults + node.args.kw_defaults:
                if default is not None:
                    self.visit(default)
        saved, self.depth = self.depth, 0
        for stmt in node.body:
            self.visit(stmt)
        self.depth = saved

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _visit_scope

    def visit_Call(self, node: ast.Call):
        resolved = self.ctx.resolve(node.func)
        if resolved in _JIT_NAMES:
            if self.depth > 0:
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f"{resolved}() evaluated inside a loop builds a fresh "
                    "wrapper (and an empty compile cache) every iteration "
                    "— hoist the jit out of the loop"))
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") \
                        and isinstance(kw.value, _UNSTABLE_STATIC):
                    self.out.append(self.rule.finding(
                        self.ctx, kw.value,
                        f"{kw.arg} given a "
                        f"{type(kw.value).__name__.lower()} — use an int "
                        "or tuple literal; non-hashable/unordered values "
                        "break or destabilize the jit cache key"))
        self.generic_visit(node)


class JitInLoop(Rule):
    id = "DTX002"
    name = "jit-in-loop"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        visitor = _LoopVisitor(self, ctx)
        visitor.visit(ctx.tree)
        return visitor.out


_IMPORT_WORK_PREFIXES = ("jax.numpy.", "jax.random.", "jax.nn.")
_IMPORT_WORK_EXACT = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.device_put", "jax.process_index",
}


class ModuleImportDeviceWork(Rule):
    id = "DTX008"
    name = "module-import-device-work"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        self._scan_body(ctx, ctx.tree.body, out, where="module import")
        return out

    def _scan_body(self, ctx, body, out, where: str):
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_body(ctx, stmt.body, out,
                                where="class body (import time)")
                continue
            if isinstance(stmt, _FUNC_NODES):
                # default args evaluate at import; bodies do not
                for default in stmt.args.defaults + stmt.args.kw_defaults:
                    if default is not None:
                        self._scan_expr(ctx, default, out,
                                        where="function default argument")
                for dec in stmt.decorator_list:
                    self._scan_expr(ctx, dec, out, where="decorator")
                continue
            self._scan_expr(ctx, stmt, out, where=where)

    def _scan_expr(self, ctx, root, out, where: str):
        stack = [root]
        while stack:
            node = stack.pop()
            # lambda/def bodies run when called, not at import
            if isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved is None:
                continue
            if resolved in _IMPORT_WORK_EXACT or any(
                    resolved.startswith(p) for p in _IMPORT_WORK_PREFIXES):
                out.append(self.finding(
                    ctx, node,
                    f"{resolved}() runs at {where}: device work during "
                    "import initializes the backend early and allocates "
                    "before mesh/platform setup — hoist it into a "
                    "function or compute it lazily"))
