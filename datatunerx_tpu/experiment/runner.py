"""ExperimentRunner: the closed loop, end to end.

    N jobs on a shared slice pool  →  continuous scoring / leaderboard
        →  winner  →  canary replica behind the gateway  →  weighted
        traffic shift  →  full rollout (or auto-rollback)

One runner owns one experiment: a ``SliceScheduler`` (elastic training), a
``ContinuousScoringWatcher`` (live leaderboard + early stop), and — when a
gateway is attached — the promotion phase. ``tick()`` advances whatever
phase the experiment is in; ``run()`` loops it. Everything the loop does
lands in ``dtx_experiment_*`` metrics and in spans under one trace id
(``dtx-exp-<name>``), merged into the gateway's trace store when a gateway
is present, so ``GET /debug/trace/dtx-exp-<name>`` shows the experiment's
phases next to the promotion's stage spans.

``main()`` is the ``dtx experiment`` CLI: run a spec file's experiment
locally against the Fake backends (``--backend fake``, a scripted
self-driving demo of the whole loop: simulated training, scores, canary
shift) or the LocalProcessBackend (``--backend local``, real trainer
subprocesses; scoring then needs per-job serving endpoints in the spec).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Callable, List, Optional

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.pool import PoolSlice, SharedSlicePool
from datatunerx_tpu.experiment.promotion import TERMINAL
from datatunerx_tpu.experiment.scheduler import SliceScheduler
from datatunerx_tpu.experiment.watcher import (
    ContinuousScoringWatcher,
    Leaderboard,
)
from datatunerx_tpu.obs.trace import Tracer, TraceStore

PHASE_TRAIN = "train"
PHASE_PROMOTE = "promote"
PHASE_DONE = "done"


class ExperimentRunner:
    def __init__(self, name: str, scheduler: SliceScheduler,
                 watcher: ContinuousScoringWatcher,
                 gateway=None,
                 serving_backend=None,
                 canary_replica_factory: Optional[Callable] = None,
                 canary_spec_fn: Optional[Callable] = None,
                 promotion_config: Optional[dict] = None,
                 traffic_fn: Optional[Callable] = None,
                 metrics: Optional[ExperimentMetrics] = None):
        self.name = name
        self.scheduler = scheduler
        self.watcher = watcher
        self.gateway = gateway
        self.serving_backend = serving_backend
        self.canary_replica_factory = canary_replica_factory
        self.canary_spec_fn = canary_spec_fn
        self.promotion_config = dict(promotion_config or {})
        self.traffic_fn = traffic_fn
        self.metrics = metrics if metrics is not None \
            else ExperimentMetrics(experiment=name)
        self.trace_id = f"dtx-exp-{name}"
        # spans land where the gateway's do, so one /debug/trace/<id> shows
        # the whole loop; without a gateway the runner keeps a private ring
        self.tracer = gateway.tracer if gateway is not None \
            else Tracer(store=TraceStore())
        self.phase = PHASE_TRAIN
        self.promotion = None
        self.canary_name = f"{name}-canary"
        self._canary_deployed = False
        self.winner = None
        self.events: List[dict] = []
        # bounded score-drain: once training is done, keep ticking the
        # watcher while final-checkpoint scores are still pending (warming
        # endpoints) before picking a winner — up to this many ticks
        self.score_drain_ticks = 100
        self._drained = 0
        self._promotion_blocked_logged = False
        self._phase_span = self.tracer.start(
            "experiment.train", trace_id=self.trace_id, experiment=name)

    # ---------------------------------------------------------------- tick
    def tick(self) -> List[dict]:
        if self.phase == PHASE_TRAIN:
            events = self.scheduler.tick()
            events += self.watcher.tick()
            if self.scheduler.done():
                # final checkpoints whose scores are still warming get a
                # bounded number of retry ticks before the verdict — a
                # winner picked off stale mid-training scores is wrong
                if (self.watcher.pending_scores > 0
                        and self._drained < self.score_drain_ticks):
                    self._drained += 1
                else:
                    events += self._finish_training()
        elif self.phase == PHASE_PROMOTE:
            events = self._tick_promotion()
        else:
            events = []
        self.events.extend(events)
        return events

    def _finish_training(self) -> List[dict]:
        # final checkpoints of just-succeeded jobs still need scoring
        events = self.watcher.tick()
        standings = self.watcher.board.standings()
        succeeded = {j.name for j in self.scheduler.succeeded()}
        ranked = [e for e in standings
                  if e.job in succeeded and e.score is not None]
        self.winner = ranked[0] if ranked else None
        self._phase_span.set(
            jobs={j.name: j.state for j in self.scheduler.jobs()},
            winner=self.winner.job if self.winner else None,
            best_score=self.winner.score if self.winner else None)
        self.tracer.finish(self._phase_span)
        if self.winner is None or self.gateway is None:
            self.phase = PHASE_DONE
            return events + [{"event": "experiment_done",
                              "winner": None if self.winner is None
                              else self.winner.job,
                              "promoted": False}]
        self.phase = PHASE_PROMOTE
        self._phase_span = self.tracer.start(
            "experiment.promote", trace_id=self.trace_id,
            winner=self.winner.job, score=self.winner.score)
        return events + [{"event": "winner", "job": self.winner.job,
                          "score": self.winner.score}]

    # ----------------------------------------------------------- promotion
    def _tick_promotion(self) -> List[dict]:
        events: List[dict] = []
        if self.promotion is None:
            started = self._start_promotion()
            if started is not None:
                events.append(started)
            return events
        if self.traffic_fn is not None:
            self.traffic_fn(self.gateway)
        state = self.promotion.tick()
        if state in TERMINAL:
            self._phase_span.set(outcome=state,
                                 reason=self.promotion.reason)
            self.tracer.finish(
                self._phase_span,
                status="ok" if state == "completed" else "error")
            self.phase = PHASE_DONE
            events.append({"event": "experiment_done",
                           "winner": self.winner.job,
                           "promoted": state == "completed",
                           "outcome": state})
        return events

    def _start_promotion(self) -> Optional[dict]:
        """Deploy the winner's serving app (serving backend), wait for it
        to report HEALTHY, put its replica in the gateway pool, start the
        weighted shift."""
        job = self.scheduler.job(self.winner.job)
        if self.serving_backend is not None and not self._canary_deployed:
            spec = (self.canary_spec_fn(job) if self.canary_spec_fn
                    else self._default_canary_spec(job))
            self.serving_backend.deploy(self.canary_name, spec)
            self._canary_deployed = True
        if self.serving_backend is not None:
            if self.serving_backend.status(self.canary_name) != "HEALTHY":
                return None  # keep waiting; backend failure = stay here
        if self.gateway.pool.get(self.canary_name) is None:
            replica = None
            if self.canary_replica_factory is not None:
                replica = self.canary_replica_factory(job)
            elif self.serving_backend is not None:
                endpoint = self.serving_backend.endpoint(self.canary_name)
                if endpoint:
                    from datatunerx_tpu.gateway.replica_pool import (
                        HTTPReplica,
                    )

                    replica = HTTPReplica(self.canary_name, endpoint)
            if replica is None:
                return None
            replica.name = self.canary_name
            self.gateway.pool.add(replica)
        try:
            self.promotion = self.gateway.start_promotion(
                self.canary_name, config=self.promotion_config,
                metrics=self.metrics, background=False)
        except ValueError as e:
            if "already active" not in str(e):
                # config error (bad schedule, empty fleet): terminal — an
                # unpromotable experiment must not crash or spin forever
                self._phase_span.set(error=str(e))
                self.tracer.finish(self._phase_span, status="error")
                self.phase = PHASE_DONE
                return {"event": "experiment_done",
                        "winner": self.winner.job, "promoted": False,
                        "error": str(e)}
            # an operator-initiated /admin/promote is mid-flight (single
            # flight): wait for it — the slot frees when it goes terminal.
            # Logged once, then silent retries each tick.
            if not self._promotion_blocked_logged:
                self._promotion_blocked_logged = True
                return {"event": "promotion_waiting", "reason": str(e)}
            return None
        self._promotion_blocked_logged = False
        # fold the promotion's spans into the experiment's trace
        self.promotion.trace_id = self.trace_id
        self.promotion._root.trace_id = self.trace_id
        return {"event": "promotion_started", "canary": self.canary_name,
                "schedule": list(self.promotion.config.schedule)}

    @staticmethod
    def _default_canary_spec(job) -> dict:
        spec = dict(job.spec.get("serve") or {})
        spec.setdefault("checkpoint_path", job.spec.get("checkpoint_dir"))
        return spec

    # ------------------------------------------------------------ blocking
    def run(self, max_ticks: int = 10_000, tick_s: float = 0.05) -> str:
        for _ in range(max_ticks):
            self.tick()
            if self.phase == PHASE_DONE:
                break
            if tick_s > 0:
                time.sleep(tick_s)
        return self.phase

    # -------------------------------------------------------------- reports
    def status(self) -> dict:
        return {
            "name": self.name,
            "phase": self.phase,
            "jobs": [j.to_dict() for j in self.scheduler.jobs()],
            "leaderboard": self.watcher.board.to_dict(),
            "winner": self.winner.job if self.winner else None,
            "promotion": (self.promotion.status()
                          if self.promotion is not None else None),
            "trace_id": self.trace_id,
        }


# --------------------------------------------------------------------- fakes

class _FakeLoopDriver:
    """Self-driving demo for ``dtx experiment --backend fake``: simulated
    training on the FakeTrainingBackend (jobs 'train' for a few ticks,
    dropping periodic eval checkpoints whose scores follow a per-job curve),
    a FakeServingBackend canary, and synthetic gateway traffic during the
    shift — the whole closed loop in-process, no models, no TPUs."""

    def __init__(self, backend, serving_backend, jobs: List[dict],
                 ticks_per_step: int = 2, steps_to_finish: int = 3):
        self.backend = backend
        self.serving = serving_backend
        self.jobs = {j["name"]: j for j in jobs}
        self.ticks_per_step = max(1, ticks_per_step)
        self.steps_to_finish = steps_to_finish
        self._ticks: dict = {}

    def advance(self):
        for name, state in list(self.backend.states.items()):
            if state not in ("Pending", "Running"):
                continue
            self.backend.states[name] = "Running"
            t = self._ticks[name] = self._ticks.get(name, 0) + 1
            if t >= self.ticks_per_step * self.steps_to_finish:
                self.backend.states[name] = "Succeeded"
        for name, state in list(self.serving.states.items()):
            if state == "PENDING":
                self.serving.states[name] = "HEALTHY"

    def checkpoints(self, job) -> List[int]:
        t = self._ticks.get(job.name, 0)
        done = self.backend.status(job.name) == "Succeeded"
        steps = t // self.ticks_per_step + (1 if done else 0)
        return list(range(1, min(steps, self.steps_to_finish) + 1))

    def score(self, job, step: int) -> float:
        base = float(self.jobs[job.name].get("fake_base_score",
                                             50 + 7 * (hash(job.name) % 5)))
        slope = float(self.jobs[job.name].get("fake_score_slope", 3.0))
        return round(base + slope * step, 2)


def _fake_traffic(gateway):
    import uuid as _uuid

    for _ in range(4):
        try:
            gateway.chat({"messages": [
                {"role": "user",
                 "content": f"probe {_uuid.uuid4().hex[:8]}"}]})
        except Exception:  # noqa: BLE001 — synthetic traffic is best-effort
            pass


def _build_fake_experiment(spec: dict) -> ExperimentRunner:
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.operator.backends import (
        FakeServingBackend,
        FakeTrainingBackend,
    )

    class _EchoEngine:
        def __init__(self, tag):
            self.tag = tag
            self.slots = 4
            self._slot_req = [None] * 4

        def chat(self, messages, **kw):
            return f"[{self.tag}] ok"

    name = spec.get("name", "experiment")
    jobs = spec.get("jobs") or []
    slices = [PoolSlice(**s) for s in (spec.get("pool", {}).get("slices")
                                       or [{"name": "s0"}, {"name": "s1"}])]
    backend = FakeTrainingBackend()
    serving = FakeServingBackend()
    driver = _FakeLoopDriver(backend, serving, jobs)
    metrics = ExperimentMetrics(experiment=name)
    scheduler = SliceScheduler(SharedSlicePool(slices), backend,
                               metrics=metrics,
                               checkpoint_probe=lambda job: max(
                                   driver.checkpoints(job) or [0]) or None)
    scoring = spec.get("scoring") or {}
    pool = ReplicaPool([InProcessReplica("fleet-0", _EchoEngine("fleet-0")),
                        InProcessReplica("fleet-1", _EchoEngine("fleet-1"))])
    gateway = Gateway(pool, model_name=name)
    watcher = ContinuousScoringWatcher(
        scheduler, driver.checkpoints, driver.score, board=Leaderboard(),
        metrics=metrics,
        early_stop_margin=scoring.get("earlyStopMargin"),
        min_evals=int(scoring.get("minEvals", 2)))
    runner = ExperimentRunner(
        name, scheduler, watcher, gateway=gateway, serving_backend=serving,
        canary_replica_factory=lambda job: InProcessReplica(
            f"{name}-canary", _EchoEngine(f"canary:{job.name}")),
        promotion_config=spec.get("promotion")
        or {"schedule": [0.25, 1.0], "min_requests": 8, "step_s": 2.0},
        traffic_fn=_fake_traffic, metrics=metrics)
    runner._fake_driver = driver
    for j in jobs:
        scheduler.add_job(j["name"], j.get("spec") or {})
    return runner


def _build_local_experiment(spec: dict, workdir: str) -> ExperimentRunner:
    from datatunerx_tpu.experiment.watcher import orbax_checkpoints_fn
    from datatunerx_tpu.operator.backends import LocalProcessBackend
    from datatunerx_tpu.scoring.builtin import score_endpoint

    name = spec.get("name", "experiment")
    slices = [PoolSlice(**s) for s in spec.get("pool", {}).get("slices", [])]
    if not slices:
        raise SystemExit("error: --backend local needs spec.pool.slices")
    backend = LocalProcessBackend(workdir)
    metrics = ExperimentMetrics(experiment=name)
    scheduler = SliceScheduler(SharedSlicePool(slices), backend,
                               metrics=metrics)
    scoring = spec.get("scoring") or {}

    def score_fn(job, step):
        endpoint = job.spec.get("score_endpoint")
        if not endpoint:
            return None
        try:
            return float(score_endpoint(
                endpoint, probes=scoring.get("probes"))["score"])
        except Exception:  # noqa: BLE001 — endpoint warming: retry next tick
            return None

    watcher = ContinuousScoringWatcher(
        scheduler, orbax_checkpoints_fn, score_fn,
        metrics=metrics,
        early_stop_margin=scoring.get("earlyStopMargin"),
        min_evals=int(scoring.get("minEvals", 2)))
    runner = ExperimentRunner(name, scheduler, watcher, metrics=metrics,
                              promotion_config=spec.get("promotion"))
    for j in spec.get("jobs") or []:
        scheduler.add_job(j["name"], j.get("spec") or {})
    return runner


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dtx experiment",
        description="Run a closed-loop experiment: N jobs on a shared "
                    "slice pool, continuous scoring, canary promotion.")
    p.add_argument("-f", "--filename", required=True,
                   help="experiment spec (JSON): name, pool.slices, jobs, "
                        "scoring, promotion")
    p.add_argument("--backend", choices=["fake", "local"], default="fake")
    p.add_argument("--workdir", default="experiment-jobs",
                   help="job working directory (local backend)")
    p.add_argument("--max_ticks", type=int, default=2000)
    p.add_argument("--tick_s", type=float, default=0.05)
    p.add_argument("--status_json", default="",
                   help="write the final experiment status to this file")
    args = p.parse_args(argv)

    with open(args.filename) as f:
        spec = json.load(f)
    if args.backend == "fake":
        runner = _build_fake_experiment(spec)
    else:
        runner = _build_local_experiment(spec, args.workdir)

    seen = 0
    for _ in range(args.max_ticks):
        if args.backend == "fake":
            runner._fake_driver.advance()
        runner.tick()
        for ev in runner.events[seen:]:
            print(f"[experiment] {json.dumps(ev)}", flush=True)
        seen = len(runner.events)
        if runner.phase == PHASE_DONE:
            break
        if args.tick_s > 0:
            time.sleep(args.tick_s)

    status = runner.status()
    print(f"[experiment] final {json.dumps(status, default=str)}",
          flush=True)
    if args.status_json:
        with open(args.status_json, "w") as f:
            json.dump(status, f, indent=1, default=str)
    ok = (runner.phase == PHASE_DONE
          and (runner.promotion is None
               or runner.promotion.state == "completed"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
