"""Closed-loop experiment plane (the paper's FinetuneExperiment, live):

    shared slice pool → elastic N-job scheduling (preempt/resume via orbax)
      → continuous scoring as eval checkpoints land (live leaderboard,
        early stop) → winner → canary replica behind the gateway →
        weighted traffic shift with auto-rollback → full rollout

Modules: ``pool`` (elastic slice inventory, mesh-shape gang fit),
``scheduler`` (fair-share + score-aware priorities, checkpoint-aware
preemption), ``watcher`` (leaderboard + early stop, scoring-controller
bridge), ``promotion`` (canary weight shift + rollback guard), ``runner``
(the loop + the ``dtx experiment`` CLI), ``metrics`` (dtx_experiment_*).
"""

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.pool import PoolSlice, SharedSlicePool
from datatunerx_tpu.experiment.promotion import (
    PromotionConfig,
    PromotionController,
)
from datatunerx_tpu.experiment.runner import ExperimentRunner
from datatunerx_tpu.experiment.scheduler import ExperimentJob, SliceScheduler
from datatunerx_tpu.experiment.watcher import (
    ContinuousScoringWatcher,
    Leaderboard,
)

__all__ = [
    "ContinuousScoringWatcher",
    "ExperimentJob",
    "ExperimentMetrics",
    "ExperimentRunner",
    "Leaderboard",
    "PoolSlice",
    "PromotionConfig",
    "PromotionController",
    "SharedSlicePool",
    "SliceScheduler",
]
