"""Canary promotion: checkpoint → canary replica → weighted traffic shift →
full rollout, with automatic rollback on regression.

The winner of an experiment never used to reach the gateway; this controller
closes that gap. Given a gateway whose pool already contains the canary
replica (spawned from the winning checkpoint — `ExperimentRunner` deploys it
via the serving backend, tests add an in-process replica), the promotion
walks a weight schedule:

  stage i: canary carries ``w`` of the traffic — its pool weight is set to
  ``w`` and every fleet replica's to ``(1-w)/n_fleet``, so the router's
  smooth-WRR share for the canary is exactly ``w``. The canary's circuit
  breaker opening (consecutive failures — already multi-request evidence)
  rolls back IMMEDIATELY; otherwise the stage holds until the canary has
  served ``min_requests`` attempts (or ``step_s`` elapses), then the
  guard runs:

    - canary error rate over the stage window > ``max_error_rate``, or
    - canary latency p95 over the STAGE'S OWN samples >
      ``max_latency_ratio`` × the fleet's p95 (from the per-replica
      outcome windows the gateway feeds from the same measurements as
      its request histograms)

  → ROLLBACK: canary weight 0, fleet restored to 1.0, promotion over.
  Otherwise the next stage begins; after the last stage (weight 1.0 — the
  fleet's weights are 0, all traffic on the canary) the promotion
  COMPLETES and the operator may drain the old replicas at leisure.

Tick-driven like the scheduler — ``tick()`` advances at most one decision;
``run()`` loops it with a sleep for the CLI/HTTP path. Every phase emits a
span into the gateway's trace store, so ``GET /debug/trace/<trace_id>``
shows the full promotion timeline, and dtx_experiment_* gauges/counters
track weight, phase and outcome.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Optional

from datatunerx_tpu.experiment.metrics import ExperimentMetrics

CANARY = "canary"
SHIFTING = "shifting"
COMPLETED = "completed"
ROLLED_BACK = "rolled_back"
TERMINAL = (COMPLETED, ROLLED_BACK)


@dataclass
class PromotionConfig:
    schedule: tuple = (0.05, 0.25, 0.5, 1.0)
    step_s: float = 30.0          # max dwell per stage without verdict
    min_requests: int = 20        # canary attempts before judging a stage
    max_error_rate: float = 0.05
    max_latency_ratio: float = 2.0  # canary p95 vs fleet p95
    min_fleet_requests: int = 5     # below this the latency guard abstains
    # below this fleet p95 the latency guard also abstains: a RATIO of
    # sub-millisecond p95s is scheduling noise, not a regression signal
    # (in-process test fleets measure tens of µs — 2x jitter is routine)
    min_fleet_p95_ms: float = 1.0
    # SLO-verdict mode (obs/slo.py): spec dicts evaluated over the
    # GATEWAY's registry with each stage as the window — the same evaluator
    # /debug/slo serves, so canary judgment and fleet SLOs are one code
    # path. Additive to the error-rate/latency guards above.
    slos: tuple = ()
    # evidence floor for the SLO guard, mirroring min_requests: a stage
    # window whose SLI saw fewer total events than this abstains — one
    # transient fleet 5xx in a thin window must not kill a promotion
    slo_min_events: int = 10

    @classmethod
    def from_dict(cls, d: dict) -> "PromotionConfig":
        kw = {}
        if d.get("schedule"):
            sched = tuple(float(w) for w in d["schedule"])
            if not sched or any(not 0.0 < w <= 1.0 for w in sched) \
                    or list(sched) != sorted(sched) or sched[-1] != 1.0:
                raise ValueError(
                    "schedule must be ascending weights in (0, 1] ending "
                    "at 1.0")
            kw["schedule"] = sched
        for k, attr in (("step_s", "step_s"),
                        ("min_requests", "min_requests"),
                        ("max_error_rate", "max_error_rate"),
                        ("max_latency_ratio", "max_latency_ratio"),
                        ("min_fleet_p95_ms", "min_fleet_p95_ms"),
                        ("slo_min_events", "slo_min_events")):
            if d.get(k) is not None:
                kw[attr] = type(getattr(cls, attr, 0.0))(d[k]) \
                    if not isinstance(d[k], bool) else d[k]
        if d.get("slos"):
            from datatunerx_tpu.obs.slo import parse_slos

            parse_slos(list(d["slos"]))  # fail loud on bad specs, HERE
            kw["slos"] = tuple(d["slos"])
        return cls(**kw)


@dataclass
class _StageWindow:
    started_at: float = 0.0
    canary_requests: int = 0
    canary_errors: int = 0


class PromotionController:
    """One promotion of one canary replica through a gateway's traffic."""

    def __init__(self, gateway, canary_name: str,
                 config: Optional[PromotionConfig] = None,
                 metrics: Optional[ExperimentMetrics] = None,
                 trace_id: str = ""):
        self.gateway = gateway
        self.canary_name = canary_name
        self.config = config or PromotionConfig()
        self.metrics = metrics
        self.trace_id = trace_id or f"dtx-promo-{uuid.uuid4().hex[:12]}"
        canary = gateway.pool.get(canary_name)
        if canary is None:
            raise ValueError(f"no replica {canary_name!r} in the pool")
        self.canary = canary
        if not self._fleet():
            raise ValueError("promotion needs at least one fleet replica "
                             "to shift traffic away from")
        self.state = CANARY
        self.stage = -1            # index into config.schedule
        self.reason = ""
        self._window = _StageWindow()
        # SLO-verdict mode: one evaluator over the gateway's registry for
        # the whole promotion; each stage begins with a sample() so the
        # guard judges exactly the stage's own traffic
        self.slo_eval = None
        if self.config.slos:
            from datatunerx_tpu.obs.slo import SLOEvaluator, parse_slos

            self.slo_eval = SLOEvaluator(
                gateway.registry, parse_slos(list(self.config.slos)))
        self._lock = threading.Lock()
        self._root = gateway.tracer.start(
            "promotion", trace_id=self.trace_id,
            canary=canary_name, schedule=list(self.config.schedule))
        self._stage_span = None
        if self.metrics is not None:
            self.metrics.set_promotion_phase(CANARY)

    # ------------------------------------------------------------- weights
    def _fleet(self):
        """The CURRENT non-canary pool — resolved live, not snapshotted at
        construction: a replica added mid-shift (autoscale, /admin/scale)
        must be folded into the weight scheme at the next application, and
        rollback/completion must reset replicas that joined after the
        promotion started."""
        return [r for r in self.gateway.pool.replicas()
                if r.name != self.canary_name]

    def current_weight(self) -> float:
        if self.state == COMPLETED:
            return 1.0
        if 0 <= self.stage < len(self.config.schedule) \
                and self.state == SHIFTING:
            return self.config.schedule[self.stage]
        return 0.0

    def _apply_weights(self, w: float):
        self.canary.weight = w
        fleet = self._fleet()
        fleet_w = (1.0 - w) / len(fleet) if w < 1.0 and fleet else 0.0
        for r in fleet:
            r.weight = fleet_w
        if self.metrics is not None:
            self.metrics.set_canary_weight(w)

    # -------------------------------------------------------------- stages
    def _begin_stage(self, idx: int):
        w = self.config.schedule[idx]
        self.stage = idx
        self.state = SHIFTING
        self._apply_weights(w)
        if self.slo_eval is not None:
            self.slo_eval.sample()  # the stage IS the SLO window
        canary_stats = self.canary.outcome_stats()
        self._window = _StageWindow(
            started_at=time.monotonic(),
            canary_requests=canary_stats["requests"],
            canary_errors=canary_stats["errors"])
        self._stage_span = self.gateway.tracer.start(
            "promotion.stage", trace_id=self.trace_id, parent="promotion",
            stage=idx, weight=w)
        if self.metrics is not None:
            self.metrics.set_promotion_phase(SHIFTING)

    def _finish_stage(self, status: str, **attrs):
        if self._stage_span is not None:
            self._stage_span.set(**attrs)
            self.gateway.tracer.finish(self._stage_span, status=status)
            self._stage_span = None

    # --------------------------------------------------------------- guard
    def _stage_stats(self) -> dict:
        s = self.canary.outcome_stats()
        reqs = s["requests"] - self._window.canary_requests
        errs = s["errors"] - self._window.canary_errors
        # latency over THIS stage's samples only (the most recent `reqs`
        # in the rolling window) — warm-up requests served before the
        # stage must not roll back a now-healthy canary
        p95 = (self.canary.outcome_stats(last_n=reqs)["latency_p95_ms"]
               if reqs else 0.0)
        return {"requests": reqs, "errors": errs,
                "error_rate": errs / reqs if reqs else 0.0,
                "latency_p95_ms": p95}

    def _fleet_p95(self) -> tuple:
        stats = [r.outcome_stats() for r in self._fleet()]
        total = sum(s["requests"] for s in stats)
        windows = [s["latency_p95_ms"] for s in stats if s["requests"]]
        return (max(windows) if windows else 0.0, total)

    def _regressed(self, stats: dict) -> Optional[str]:
        # SLO verdicts first, BEFORE the canary-traffic gate: the SLOs
        # judge the gateway's whole registry over the stage window, so a
        # fleet-wide breach must roll back even a stage that routed zero
        # requests to the canary
        if self.slo_eval is not None:
            from datatunerx_tpu.obs.slo import violations

            judgeable = [v for v in self.slo_eval.verdicts()
                         if v.get("total", 0) >= self.config.slo_min_events]
            broken = violations(judgeable)
            if broken:
                return broken[0]  # rollback reason NAMES the objective
        if stats["requests"] == 0:
            return None  # nothing else to judge
        if stats["error_rate"] > self.config.max_error_rate:
            return (f"canary error rate {stats['error_rate']:.2%} > "
                    f"{self.config.max_error_rate:.2%} over "
                    f"{stats['requests']} requests")
        fleet_p95, fleet_reqs = self._fleet_p95()
        if (fleet_reqs >= self.config.min_fleet_requests
                and fleet_p95 >= self.config.min_fleet_p95_ms
                and stats["latency_p95_ms"]
                > self.config.max_latency_ratio * fleet_p95):
            return (f"canary latency p95 {stats['latency_p95_ms']:.1f}ms > "
                    f"{self.config.max_latency_ratio:g}x fleet p95 "
                    f"{fleet_p95:.1f}ms")
        return None

    # ---------------------------------------------------------------- tick
    def tick(self) -> str:
        """Advance at most one decision; returns the current state."""
        with self._lock:
            return self._tick_locked()

    def abort(self, reason: str = "aborted") -> str:
        """Force-terminate a live promotion (rollback to fleet weights) —
        gateway shutdown mid-promotion calls this so the background run()
        loop goes terminal instead of ticking against a closed gateway."""
        with self._lock:
            if self.state not in TERMINAL:
                self._rollback(reason, self._stage_stats())
            return self.state

    def _tick_locked(self) -> str:
        if self.state in TERMINAL:
            return self.state
        if self.state == CANARY:
            self._begin_stage(0)
            return self.state
        stats = self._stage_stats()
        # the breaker is the one IMMEDIATE tripwire: it only opens on
        # consecutive failures (threshold 3 by default), which is already
        # multi-request evidence — everything else waits for the evidence
        # gate below, so one transient error can't kill a promotion
        if self.canary.breaker.state == "open":
            self._rollback("canary circuit breaker opened", stats)
            return self.state
        dwell = time.monotonic() - self._window.started_at
        if (stats["requests"] < self.config.min_requests
                and dwell < self.config.step_s):
            return self.state  # keep gathering evidence
        reason = self._regressed(stats)
        if reason is not None:
            self._rollback(reason, stats)
            return self.state
        self._finish_stage("ok", **stats)
        if self.stage + 1 < len(self.config.schedule):
            self._begin_stage(self.stage + 1)
        else:
            self._complete(stats)
        return self.state

    def _rollback(self, reason: str, stats: dict):
        self._finish_stage("error", error=reason, **stats)
        self._apply_weights(0.0)
        for r in self._fleet():
            r.weight = 1.0
        self.state = ROLLED_BACK
        self.reason = reason
        self._root.set(outcome=ROLLED_BACK, error=reason)
        self.gateway.tracer.finish(self._root, status="error")
        if self.metrics is not None:
            self.metrics.set_promotion_phase(ROLLED_BACK)
            self.metrics.promotion_finished(ROLLED_BACK)

    def _complete(self, stats: dict):
        self._apply_weights(1.0)
        self.state = COMPLETED
        self._root.set(outcome=COMPLETED, **stats)
        self.gateway.tracer.finish(self._root, status="ok")
        if self.metrics is not None:
            self.metrics.set_promotion_phase(COMPLETED)
            self.metrics.promotion_finished(COMPLETED)

    # ----------------------------------------------------------- blocking
    def run(self, poll_s: float = 0.25,
            timeout_s: Optional[float] = None) -> str:
        """Loop ``tick`` until terminal (the /admin/promote background
        thread and the CLI use this; tests drive ``tick`` directly)."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while self.tick() not in TERMINAL:
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    if self.state not in TERMINAL:
                        self._rollback("promotion timed out",
                                       self._stage_stats())
                break
            time.sleep(poll_s)
        return self.state

    # ------------------------------------------------------------- reports
    def status(self) -> dict:
        out = {
            "canary": self.canary_name,
            "state": self.state,
            "stage": self.stage,
            "weight": round(self.current_weight(), 4),
            "schedule": list(self.config.schedule),
            "reason": self.reason,
            "trace_id": self.trace_id,
        }
        if self.slo_eval is not None:
            out["slos"] = [
                {"name": v["name"], "compliant": v["compliant"],
                 "compliance": v["compliance"]}
                for v in self.slo_eval.verdicts()]
        return out
