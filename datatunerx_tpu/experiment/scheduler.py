"""Slice-pool scheduler: N experiment jobs running elastically on a
SharedSlicePool.

The operator's FinetuneExperiment fan-out gives every job its own dedicated
resources and waits. This scheduler is the elastic version the paper's
closed loop needs: jobs queue for slices, gang-schedule by mesh shape
(``experiment/pool.py`` → ``capacity._mesh_shape_from``), get preempted when
the pool shrinks, and resume later **from their latest orbax checkpoint** —
the trainer's existing ``--resume`` path (``training/checkpoint.py``) makes
a resubmission with the same ``--output_dir`` fast-forward instead of
restart, so preemption costs one checkpoint interval, not the run.

Priorities are fair-share + score-aware:

- a RUNNING job's priority is its latest leaderboard score (fed by the
  continuous-scoring watcher via ``set_score``) — early-leading jobs keep
  their slices; unscored jobs rank below any scored one;
- when the pool shrinks, the LOWEST-priority running job is preempted
  (ties: the job with the least runtime loses, it has the least sunk work);
- waiting jobs (pending or preempted) are admitted leaders-first, ties
  broken by least cumulative runtime (fair share), then FIFO.

Everything is tick-driven and synchronous: ``tick()`` polls the backend,
admits, and returns the events it caused — tests and the runner drive it
explicitly, no background threads.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.pool import PoolSlice, SharedSlicePool

PENDING = "Pending"
RUNNING = "Running"
PREEMPTED = "Preempted"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
STOPPED = "Stopped"

ACTIVE_STATES = (PENDING, RUNNING, PREEMPTED)
_NO_SCORE = float("-inf")


def orbax_steps(directory: Optional[str]) -> List[int]:
    """Saved steps in a checkpoint dir (ascending), read through the same
    orbax CheckpointManager the trainer saves/restores with. [] = no dir
    configured, nothing saved yet, or an unreadable dir — the ONE listing
    helper behind both the scheduler's resume probe and the watcher's
    eval-checkpoint feed."""
    if not directory:
        return []
    try:
        from datatunerx_tpu.training.checkpoint import CheckpointManager

        mngr = CheckpointManager(directory)
        try:
            return mngr.all_steps()
        finally:
            mngr.close()
    except Exception:  # noqa: BLE001 — a probe failure must not block jobs
        return []


def orbax_checkpoint_probe(job: "ExperimentJob") -> Optional[int]:
    """Latest checkpoint step the job's resume will fast-forward to
    (None = nothing saved — the job restarts from step 0)."""
    steps = orbax_steps(job.spec.get("checkpoint_dir"))
    return steps[-1] if steps else None


class ExperimentJob:
    """Scheduler-side record of one fine-tune job."""

    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = dict(spec)
        self.state = PENDING
        self.score: Optional[float] = None
        self.enqueued_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.runtime_s = 0.0  # accumulated across preemptions
        self.preemptions = 0
        self.resumes = 0
        self.resume_step: Optional[int] = None
        self.stop_reason = ""

    @property
    def parameters(self) -> dict:
        return self.spec.get("parameters") or {}

    def _accumulate_runtime(self):
        if self.started_at is not None:
            self.runtime_s += time.monotonic() - self.started_at
            self.started_at = None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "state": self.state, "score": self.score,
            "preemptions": self.preemptions, "resumes": self.resumes,
            "resumeStep": self.resume_step,
            "stopReason": self.stop_reason,
            "runtimeS": round(self.runtime_s + (
                time.monotonic() - self.started_at
                if self.started_at is not None else 0.0), 3),
        }


class SliceScheduler:
    """Elastic gang scheduler over a SharedSlicePool + TrainingBackend."""

    def __init__(self, pool: SharedSlicePool, backend,
                 metrics: Optional[ExperimentMetrics] = None,
                 checkpoint_probe: Callable[[ExperimentJob], Optional[int]]
                 = orbax_checkpoint_probe):
        self.pool = pool
        self.backend = backend
        self.metrics = metrics
        self.checkpoint_probe = checkpoint_probe
        self._jobs: Dict[str, ExperimentJob] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- queries
    def jobs(self) -> List[ExperimentJob]:
        with self._lock:
            return list(self._jobs.values())

    def job(self, name: str) -> Optional[ExperimentJob]:
        with self._lock:
            return self._jobs.get(name)

    def active(self) -> List[ExperimentJob]:
        return [j for j in self.jobs() if j.state in ACTIVE_STATES]

    def done(self) -> bool:
        return not self.active()

    def succeeded(self) -> List[ExperimentJob]:
        return [j for j in self.jobs() if j.state == SUCCEEDED]

    # ------------------------------------------------------------ lifecycle
    def add_job(self, name: str, spec: dict) -> ExperimentJob:
        with self._lock:
            if name in self._jobs:
                raise ValueError(f"job {name!r} already in the experiment")
            job = self._jobs[name] = ExperimentJob(name, spec)
        return job

    def set_score(self, name: str, score: float) -> None:
        job = self.job(name)
        if job is not None:
            job.score = float(score)

    # ------------------------------------------------------------ priority
    @staticmethod
    def _priority(job: ExperimentJob) -> float:
        return job.score if job.score is not None else _NO_SCORE

    def _admission_order(self, waiting: List[ExperimentJob]
                         ) -> List[ExperimentJob]:
        return sorted(waiting, key=lambda j: (
            -self._priority(j), j.runtime_s, j.enqueued_at))

    def _victim(self, for_job: Optional[ExperimentJob] = None
                ) -> Optional[ExperimentJob]:
        """Lowest-priority RUNNING job; with ``for_job`` set, only victims
        whose HELD SLICE the contender's mesh shape actually tiles count —
        evicting a job whose slice the contender can't use would burn a
        checkpoint interval for nothing (and thrash forever)."""
        from datatunerx_tpu.experiment.pool import mesh_fits

        running = [j for j in self.jobs() if j.state == RUNNING]
        if for_job is not None:
            usable = []
            for j in running:
                s = self.pool.assignment(j.name)
                if s is not None and mesh_fits(for_job.parameters, s.chips):
                    usable.append(j)
            running = usable
        if not running:
            return None
        return min(running, key=lambda j: (self._priority(j), j.runtime_s,
                                           j.name))

    # ---------------------------------------------------------------- tick
    def tick(self) -> List[dict]:
        """Poll terminal states, admit waiting jobs onto free slices.
        Returns the events performed (for logs/spans)."""
        events: List[dict] = []
        for job in self.jobs():
            if job.state != RUNNING:
                continue
            st = self.backend.status(job.name)
            if st == "Succeeded":
                self._terminate(job, SUCCEEDED)
                events.append({"event": "succeeded", "job": job.name})
            elif st in ("Failed", "NotFound"):
                self._terminate(job, FAILED)
                events.append({"event": "failed", "job": job.name})
        waiting = [j for j in self.jobs() if j.state in (PENDING, PREEMPTED)]
        for job in self._admission_order(waiting):
            s = self.pool.acquire(job.name, job.parameters)
            if s is None:
                # score-aware eviction: a displaced leader takes a slice
                # back from a STRICTLY lower-priority running job (both
                # scored — unscored contenders never evict anyone) whose
                # slice the leader's mesh actually fits, so a pool shrink
                # lands on the scoreboard's tail, not its head
                victim = self._victim(for_job=job)
                if (victim is not None and job.score is not None
                        and victim.score is not None
                        and self._priority(job) > self._priority(victim)):
                    self.preempt(victim.name)
                    events.append({"event": "evicted", "job": victim.name,
                                   "for": job.name})
                    s = self.pool.acquire(job.name, job.parameters)
                if s is None:
                    continue
            events.append(self._launch(job, s))
        self._update_gauges()
        return events

    def _launch(self, job: ExperimentJob, s: PoolSlice) -> dict:
        resumed = job.state == PREEMPTED
        spec = dict(job.spec)
        # fresh copy, never an alias into job.spec: writing the resume
        # marker through a shared dict would mutate the job's own spec and
        # leak a stale step into later submissions
        spec["env"] = dict(job.spec.get("env") or {})
        spec["slice"] = s.name
        spec["topology"] = s.topology
        spec["node_selector"] = s.node_selector
        if resumed and job.resume_step is not None:
            # informational: the trainer resumes from --output_dir's latest
            # orbax step regardless; the env var lets logs/tests see what
            # the scheduler expected the restore path to find
            spec["env"]["DTX_RESUME_FROM_STEP"] = str(job.resume_step)
        else:
            spec["env"].pop("DTX_RESUME_FROM_STEP", None)
        self.backend.submit(job.name, spec)
        job.state = RUNNING
        job.started_at = time.monotonic()
        if resumed:
            job.resumes += 1
            if self.metrics is not None:
                self.metrics.resumed()
        return {"event": "resumed" if resumed else "started",
                "job": job.name, "slice": s.name,
                "resume_step": job.resume_step if resumed else None}

    def _terminate(self, job: ExperimentJob, state: str):
        job._accumulate_runtime()
        job.state = state
        self.pool.release(job.name)

    # ---------------------------------------------------------- preemption
    def preempt(self, name: str) -> Optional[int]:
        """Checkpoint-aware preemption: stop the job's processes, record
        the latest orbax step it will resume from, free its slice. Returns
        the resume step (None = no checkpoint yet)."""
        job = self.job(name)
        if job is None or job.state != RUNNING:
            return None
        self.backend.delete(job.name)
        job._accumulate_runtime()
        job.resume_step = self.checkpoint_probe(job)
        job.state = PREEMPTED
        job.preemptions += 1
        self.pool.release(job.name)
        if self.metrics is not None:
            self.metrics.preempted()
        self._update_gauges()
        return job.resume_step

    def shrink(self, slice_name: str) -> Optional[str]:
        """Remove a slice from the pool, preempting its holder if any
        (the hardware is going away — whoever runs on it must checkpoint
        off). Returns the preempted job's name (None = the slice was free).
        The slice is removed FIRST and the preemption targets whoever
        remove_slice reports displaced — preempting a peeked holder before
        removal would race a concurrent tick() re-acquiring the just-freed
        slice, leaving that job running on reclaimed hardware.
        If the displaced job leads the scoreboard, the next ``tick`` gives
        it a slice back by evicting a lower-priority job (see tick's
        eviction pass) — leaders keep *a* slice, not a specific one."""
        holder = self.pool.remove_slice(slice_name)
        if holder is not None:
            self.preempt(holder)
        self._update_gauges()
        return holder

    def grow(self, s: PoolSlice) -> None:
        self.pool.add_slice(s)
        self._update_gauges()

    # ---------------------------------------------------------- early stop
    def stop(self, name: str, reason: str = "") -> bool:
        """Stop a job for good (continuous-scoring early stop): its slice
        frees for the remaining contenders and it will not resume."""
        job = self.job(name)
        if job is None or job.state not in ACTIVE_STATES:
            return False
        if job.state == RUNNING:
            self.backend.delete(job.name)
        job._accumulate_runtime()
        job.state = STOPPED
        job.stop_reason = reason
        self.pool.release(job.name)
        if self.metrics is not None and reason == "early_stop":
            self.metrics.early_stopped()
        self._update_gauges()
        return True

    # -------------------------------------------------------------- gauges
    def _update_gauges(self):
        if self.metrics is None:
            return
        counts: Dict[str, int] = {}
        for j in self.jobs():
            counts[j.state] = counts.get(j.state, 0) + 1
        self.metrics.set_job_states(counts)
        self.metrics.set_pool(self.pool.free_count(), self.pool.held_count())
