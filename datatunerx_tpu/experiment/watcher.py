"""Continuous scoring: a live leaderboard instead of a terminal verdict.

The operator's pipeline scores once, after a job finishes. The paper's loop
wants the opposite: every periodic eval checkpoint gets scored AS IT LANDS,
so the experiment carries a leaderboard while jobs still train — which is
what makes score-aware scheduling (leaders keep slices) and early-stop
(clear losers free capacity) possible at all.

Pieces:

- ``Leaderboard`` — per-job score history + current leader;
- ``ContinuousScoringWatcher`` — tick-driven: for each active job, list the
  eval checkpoints newer than the last scored one (``checkpoints_fn``),
  score each (``score_fn``), feed the board, the scheduler's priorities and
  the dtx_experiment_* metrics; flag clear losers for early stop;
- default providers for the real path: ``orbax_checkpoints_fn`` lists a
  job's saved steps through the trainer's CheckpointManager, and
  ``scoring_cr_score`` drives the EXISTING ``scoring/`` controller (a
  Scoring CR against a serving endpoint — the generative-eval path the
  serving engine already implements) and returns the numeric score.

Tests and the fake-backend CLI inject fake ``checkpoints_fn``/``score_fn``;
the contracts are one-call-per-checkpoint and a plain float score.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.scheduler import (
    RUNNING,
    SUCCEEDED,
    ExperimentJob,
    SliceScheduler,
    orbax_steps,
)


class ScoreEntry:
    __slots__ = ("job", "score", "step", "history")

    def __init__(self, job: str):
        self.job = job
        self.score: Optional[float] = None
        self.step: Optional[int] = None
        self.history: List[Tuple[int, float]] = []

    @property
    def evals(self) -> int:
        return len(self.history)

    def to_dict(self) -> dict:
        return {"job": self.job, "score": self.score, "step": self.step,
                "evals": self.evals, "history": list(self.history)}


class Leaderboard:
    """Thread-safe live standings; scores are floats, higher is better."""

    def __init__(self):
        self._entries: Dict[str, ScoreEntry] = {}
        self._lock = threading.Lock()

    def update(self, job: str, step: int, score: float) -> ScoreEntry:
        with self._lock:
            e = self._entries.get(job)
            if e is None:
                e = self._entries[job] = ScoreEntry(job)
            e.score = float(score)
            e.step = int(step)
            e.history.append((int(step), float(score)))
            return e

    def entry(self, job: str) -> Optional[ScoreEntry]:
        with self._lock:
            return self._entries.get(job)

    def standings(self) -> List[ScoreEntry]:
        with self._lock:
            entries = list(self._entries.values())
        return sorted(entries,
                      key=lambda e: (-(e.score if e.score is not None
                                       else float("-inf")), e.job))

    def leader(self) -> Optional[ScoreEntry]:
        standings = self.standings()
        return standings[0] if standings and standings[0].score is not None \
            else None

    def to_dict(self) -> dict:
        return {"standings": [e.to_dict() for e in self.standings()]}


# ------------------------------------------------------------ real providers

def orbax_checkpoints_fn(job: ExperimentJob) -> List[int]:
    """All saved steps in the job's checkpoint dir — the scheduler's
    listing helper, shared so a checkpoint-layout change lands once."""
    return orbax_steps(job.spec.get("checkpoint_dir"))


def scoring_cr_score(store, controller, name: str, endpoint: str,
                     namespace: str = "default",
                     probes: Optional[list] = None,
                     model: Optional[str] = None,
                     max_attempts: int = 3) -> Optional[float]:
    """Score one checkpoint by driving the EXISTING scoring controller: a
    Scoring CR pointed at the serving endpoint (the engine behind it does
    the generative eval), reconciled until ``status.score`` lands. Returns
    the score as float, or None when the endpoint stayed unreachable within
    ``max_attempts`` reconciles."""
    from datatunerx_tpu.operator.api import ObjectMeta, Scoring
    from datatunerx_tpu.operator.store import AlreadyExists

    spec: dict = {"inferenceService": endpoint}
    if probes:
        spec["probes"] = probes
    if model:
        spec["model"] = model
    scoring = Scoring(metadata=ObjectMeta(name=name, namespace=namespace),
                      spec=spec)
    try:
        store.create(scoring)
    except AlreadyExists:
        scoring = store.get(Scoring, name, namespace)
    for _ in range(max_attempts):
        scoring = store.get(Scoring, name, namespace)
        if scoring.status.get("score") is not None:
            break
        controller.reconcile(store, scoring)
    scoring = store.get(Scoring, name, namespace)
    raw = scoring.status.get("score")
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


# ------------------------------------------------------------------ watcher

class ContinuousScoringWatcher:
    """Tick-driven scorer of periodic eval checkpoints.

    ``checkpoints_fn(job) -> [step, ...]`` lists a job's saved eval
    checkpoints (ascending); ``score_fn(job, step) -> float`` evaluates
    one. Scores feed the leaderboard, the scheduler's priorities, and —
    when ``early_stop_margin`` is set — the early-stop verdicts: a job
    with ``min_evals`` scores trailing the leader (also at ``min_evals``)
    by more than the margin is stopped to free its slice.
    """

    def __init__(self, scheduler: SliceScheduler,
                 checkpoints_fn: Callable[[ExperimentJob], List[int]],
                 score_fn: Callable[[ExperimentJob, int], Optional[float]],
                 board: Optional[Leaderboard] = None,
                 metrics: Optional[ExperimentMetrics] = None,
                 early_stop_margin: Optional[float] = None,
                 min_evals: int = 2):
        self.scheduler = scheduler
        self.checkpoints_fn = checkpoints_fn
        self.score_fn = score_fn
        self.board = board if board is not None else Leaderboard()
        self.metrics = metrics
        self.early_stop_margin = early_stop_margin
        self.min_evals = max(1, int(min_evals))
        self._last_scored: Dict[str, int] = {}
        # checkpoints seen but not yet scored on the LAST tick (score_fn
        # returned None — endpoint warming). The runner reads this to keep
        # the training phase open until the final checkpoints' scores land
        # instead of picking a winner off stale mid-training scores.
        self.pending_scores = 0

    def tick(self) -> List[dict]:
        events: List[dict] = []
        pending = 0
        for job in self.scheduler.jobs():
            # succeeded jobs still get their FINAL checkpoint scored —
            # the terminal verdict rides the same path as the live ones
            if job.state not in (RUNNING, SUCCEEDED):
                continue
            last = self._last_scored.get(job.name, -1)
            for step in self.checkpoints_fn(job):
                if step <= last:
                    continue
                score = self.score_fn(job, step)
                if score is None:
                    pending += 1
                    continue  # endpoint not ready — retried next tick
                self._last_scored[job.name] = step
                self.board.update(job.name, step, score)
                self.scheduler.set_score(job.name, score)
                if self.metrics is not None:
                    self.metrics.scored(job.name, score)
                events.append({"event": "scored", "job": job.name,
                               "step": step, "score": score})
        self.pending_scores = pending
        leader = self.board.leader()
        if leader is not None and self.metrics is not None:
            self.metrics.set_best(leader.score)
        events.extend(self._early_stop(leader))
        return events

    def _early_stop(self, leader: Optional[ScoreEntry]) -> List[dict]:
        if (self.early_stop_margin is None or leader is None
                or leader.evals < self.min_evals):
            return []
        events: List[dict] = []
        for job in self.scheduler.jobs():
            if job.state != RUNNING or job.name == leader.job:
                continue
            e = self.board.entry(job.name)
            if (e is None or e.score is None or e.evals < self.min_evals
                    or leader.score - e.score <= self.early_stop_margin):
                continue
            if self.scheduler.stop(job.name, reason="early_stop"):
                events.append({"event": "early_stop", "job": job.name,
                               "score": e.score, "leader": leader.job,
                               "leader_score": leader.score})
        return events
