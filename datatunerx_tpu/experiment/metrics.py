"""dtx_experiment_* metrics: the experiment plane's view of the closed loop.

One ``ExperimentMetrics`` instance per experiment, wrapping a shared
``obs.metrics.Registry`` so the exposition obeys the same invariants the
gateway/serving/training planes hold (metrics_lint validates this plane the
same way). The scheduler, watcher and promotion controller record through
the methods here — no raw registry access from the loop code, so metric
names/labels live in exactly one place.
"""

from __future__ import annotations

from typing import Optional

from datatunerx_tpu.obs.metrics import Registry, set_build_info

PROMOTION_PHASES = ("idle", "canary", "shifting", "completed", "rolled_back")


class ExperimentMetrics:
    def __init__(self, registry: Optional[Registry] = None,
                 experiment: str = ""):
        self.registry = registry if registry is not None else Registry()
        self.experiment = experiment
        set_build_info(self.registry, "experiment")
        g = self.registry.gauge
        c = self.registry.counter
        self._jobs = g("dtx_experiment_jobs",
                       "Jobs by scheduler state (pending/running/preempted/"
                       "succeeded/failed/stopped).")
        self._slices = g("dtx_experiment_pool_slices",
                         "Pool slices by occupancy (free/held).")
        self._preempt = c("dtx_experiment_preemptions_total",
                          "Jobs preempted off a slice (pool shrink or "
                          "priority eviction).")
        self._resume = c("dtx_experiment_resumes_total",
                         "Preempted jobs resumed from their latest orbax "
                         "checkpoint.")
        self._early = c("dtx_experiment_early_stops_total",
                        "Jobs stopped early by the continuous-scoring "
                        "watcher to free pool capacity.")
        self._evals = c("dtx_experiment_evals_total",
                        "Eval checkpoints scored by the watcher.")
        self._score = g("dtx_experiment_job_score",
                        "Latest leaderboard score per job.")
        self._best = g("dtx_experiment_best_score",
                       "Current leaderboard leader's score.")
        self._weight = g("dtx_experiment_canary_weight",
                         "Traffic fraction currently routed to the "
                         "promotion canary (0 = no active canary).")
        self._phase = g("dtx_experiment_promotion_phase",
                        "One-hot promotion state "
                        "(idle/canary/shifting/completed/rolled_back).")
        self._promotions = c("dtx_experiment_promotions_total",
                             "Finished promotions by outcome "
                             "(completed/rolled_back).")
        self._rollbacks = c("dtx_experiment_rollbacks_total",
                            "Promotions rolled back after a canary "
                            "regression (error rate or latency).")
        self.set_promotion_phase("idle")

    # ------------------------------------------------------------ scheduler
    def set_job_states(self, counts: dict) -> None:
        self._jobs.clear()
        for state, n in sorted(counts.items()):
            self._jobs.set(n, {"state": str(state).lower()})

    def set_pool(self, free: int, held: int) -> None:
        self._slices.set(free, {"state": "free"})
        self._slices.set(held, {"state": "held"})

    def preempted(self) -> None:
        self._preempt.inc()

    def resumed(self) -> None:
        self._resume.inc()

    def early_stopped(self) -> None:
        self._early.inc()

    # -------------------------------------------------------------- scoring
    def scored(self, job: str, score: float) -> None:
        self._evals.inc()
        self._score.set(score, {"job": job})

    def set_best(self, score: float) -> None:
        self._best.set(score)

    # ------------------------------------------------------------ promotion
    def set_canary_weight(self, weight: float) -> None:
        self._weight.set(weight)

    def set_promotion_phase(self, phase: str) -> None:
        for p in PROMOTION_PHASES:
            self._phase.set(1 if p == phase else 0, {"phase": p})

    def promotion_finished(self, outcome: str) -> None:
        self._promotions.inc({"outcome": outcome})
        if outcome == "rolled_back":
            self._rollbacks.inc()

    # ------------------------------------------------------------- scrape
    def expose(self) -> str:
        return self.registry.expose()
