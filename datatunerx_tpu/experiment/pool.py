"""Shared slice pool: one inventory of TPU slices feeding N concurrent jobs.

The operator's ``placement.SlicePool`` (PR r4) is a static inventory: a job
acquires a slice and keeps it until it terminates. An experiment wants the
opposite — N jobs *sharing* a pool that can grow and shrink while they run,
with the scheduler preempting and resuming jobs as capacity moves. This pool
is that elastic inventory:

- **gang-fit by mesh shape**: a job fits a slice iff the EXACT mesh the SPMD
  driver would build tiles the slice's chips — decided by
  ``operator/capacity.py::_mesh_shape_from``, the same parser/absorber the
  trainer uses, so admission here equals what the job would do on-slice;
- **elasticity**: ``add_slice``/``remove_slice`` reshape the pool live; a
  removal of a held slice reports the displaced job so the scheduler can
  preempt (checkpoint) and later resume it elsewhere.

Thread-safe like the operator pool: scheduler ticks, an admin shrink and a
metrics scrape may all touch it concurrently.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class PoolSlice:
    """One schedulable TPU slice (or its fake/CPU stand-in)."""

    def __init__(self, name: str, chips: int = 8, topology: str = "2x4",
                 node_selector: Optional[dict] = None):
        self.name = name
        self.chips = int(chips)
        self.topology = topology
        self.node_selector = dict(node_selector or {})

    def to_dict(self) -> dict:
        return {"name": self.name, "chips": self.chips,
                "topology": self.topology,
                "nodeSelector": self.node_selector}


def mesh_fits(parameters: dict, n_chips: int) -> bool:
    """True iff the job's meshShape tiles ``n_chips`` — the trainer's own
    mesh builder is the oracle (capacity._mesh_shape_from raises the same
    ValueError the SPMD driver would raise on-slice)."""
    from datatunerx_tpu.operator.capacity import _mesh_shape_from

    try:
        _mesh_shape_from(dict(parameters or {}), n_chips)
    except (ValueError, TypeError):
        return False
    return True


class SharedSlicePool:
    """Elastic slice inventory for one experiment."""

    def __init__(self, slices: Optional[List[PoolSlice]] = None):
        self._slices: Dict[str, PoolSlice] = {}
        self._held: Dict[str, str] = {}  # slice name -> job name
        self._lock = threading.Lock()
        for s in slices or []:
            self.add_slice(s)

    # ------------------------------------------------------------- queries
    def slices(self) -> List[PoolSlice]:
        with self._lock:
            return list(self._slices.values())

    def size(self) -> int:
        with self._lock:
            return len(self._slices)

    def free_count(self) -> int:
        with self._lock:
            return len(self._slices) - len(self._held)

    def held_count(self) -> int:
        with self._lock:
            return len(self._held)

    def holder_of(self, slice_name: str) -> Optional[str]:
        with self._lock:
            return self._held.get(slice_name)

    def assignment(self, job: str) -> Optional[PoolSlice]:
        with self._lock:
            for sname, holder in self._held.items():
                if holder == job:
                    return self._slices[sname]
        return None

    # ------------------------------------------------------------ lifecycle
    def add_slice(self, s: PoolSlice) -> None:
        with self._lock:
            if s.name in self._slices:
                raise ValueError(f"slice {s.name!r} already in the pool")
            self._slices[s.name] = s

    def remove_slice(self, name: str) -> Optional[str]:
        """Remove a slice from the pool. Returns the displaced job's name
        when the slice was held (the scheduler preempts it), else None.
        Unknown names are a no-op (idempotent shrink)."""
        with self._lock:
            if name not in self._slices:
                return None
            del self._slices[name]
            return self._held.pop(name, None)

    def acquire(self, job: str, parameters: Optional[dict] = None
                ) -> Optional[PoolSlice]:
        """Smallest free slice the job's mesh shape tiles; idempotent per
        job (re-acquiring returns the held slice)."""
        while True:
            with self._lock:
                for sname, holder in self._held.items():
                    if holder == job:
                        return self._slices[sname]
                free = sorted(
                    (s for s in self._slices.values()
                     if s.name not in self._held),
                    key=lambda s: (s.chips, s.name))
            # fit check outside the lock: _mesh_shape_from imports the mesh
            # helpers and may be slow on first call
            chosen = next(
                (s for s in free if mesh_fits(parameters or {}, s.chips)),
                None)
            if chosen is None:
                return None
            with self._lock:
                # the slice may have been taken/removed while we fit-checked
                if (chosen.name in self._slices
                        and chosen.name not in self._held):
                    self._held[chosen.name] = job
                    return chosen

    def release(self, job: str) -> None:
        with self._lock:
            for sname, holder in list(self._held.items()):
                if holder == job:
                    del self._held[sname]
