"""Model configurations for the llama-family decoder.

The reference platform targets Llama-2-7B LoRA SFT (reference
pkg/util/generate/generate.go:21, internal/controller/finetune/finetunejob_controller.go:310)
and its BASELINE configs add Mistral-7B (full-param FSDP) and Qwen1.5-14B (QLoRA).
All three are the same decoder family: RMSNorm + RoPE + GQA + SwiGLU, differing in
dims, kv-head count, qkv bias (Qwen) and sliding window (Mistral) — so one
implementation with a config dataclass covers the model inventory.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    # RoPE scaling: reference exposes --rope_scaling {linear,dynamic}
    # (reference cmd/tuning/parser.py:57-60); None disables.
    rope_scaling_type: Optional[str] = None
    rope_scaling_factor: float = 1.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # Qwen1.5 uses bias on q/k/v projections
    sliding_window: Optional[int] = None  # Mistral local attention window
    # remat ("gradient checkpointing", reference cmd/tuning/train.py:205) policy:
    # "none" | "full" | "dots" (checkpoint_dots_with_no_batch_dims)
    remat: str = "full"
    # attention implementation: "xla" (einsum softmax) | "flash" (Pallas) |
    # "ring" (sequence-parallel ring attention over a mesh axis)
    attention_impl: str = "xla"
    # base-weight quantization: None | "int8" | "int4"/"nf4" (QLoRA).
    # Replaces bitsandbytes (reference cmd/tuning/train.py:224-234).
    quantization: Optional[str] = None
    quant_impl: str = "xla"  # "xla" | "pallas"
    # paged-decode attention kernel (ops/pallas_paged_attention.py): True
    # routes single-token decode over a block-table cache through the Pallas
    # in-place kernel instead of the XLA gather; engages only when the cache
    # is paged, T == 1, and sliding_window is None (everything else keeps
    # the gather oracle). Resolved by the serving engine from its
    # --paged_kernel auto|on|off flag; training never sets it.
    paged_kernel: bool = False

    def __post_init__(self):
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.hidden_size // self.num_heads)
        assert self.num_heads % self.num_kv_heads == 0
        if self.rope_scaling_type is not None:
            assert self.rope_scaling_type in ("linear", "dynamic"), self.rope_scaling_type

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


PRESETS = {
    # Debug-scale configs for tests and CPU smoke runs.
    "debug": ModelConfig(
        name="debug", vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
    ),
    "debug-350m": ModelConfig(
        name="debug-350m", vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=20, num_heads=16, num_kv_heads=16, max_seq_len=2048,
    ),
    "tinyllama-1.1b": ModelConfig(
        name="tinyllama-1.1b", vocab_size=32000, hidden_size=2048,
        intermediate_size=5632, num_layers=22, num_heads=32, num_kv_heads=4,
        max_seq_len=2048,
    ),
    "llama2-7b": ModelConfig(
        name="llama2-7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=11008, num_layers=32, num_heads=32, num_kv_heads=32,
        max_seq_len=4096,
    ),
    "llama2-13b": ModelConfig(
        name="llama2-13b", vocab_size=32000, hidden_size=5120,
        intermediate_size=13824, num_layers=40, num_heads=40, num_kv_heads=40,
        max_seq_len=4096,
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        max_seq_len=8192, sliding_window=4096, rms_norm_eps=1e-5,
    ),
    "qwen1.5-14b": ModelConfig(
        name="qwen1.5-14b", vocab_size=152064, hidden_size=5120,
        intermediate_size=13696, num_layers=40, num_heads=40, num_kv_heads=40,
        max_seq_len=8192, rope_theta=1_000_000.0, attention_bias=True,
        rms_norm_eps=1e-6,
    ),
    "qwen1.5-7b": ModelConfig(
        name="qwen1.5-7b", vocab_size=151936, hidden_size=4096,
        intermediate_size=11008, num_layers=32, num_heads=32, num_kv_heads=32,
        max_seq_len=8192, rope_theta=1_000_000.0, attention_bias=True,
        rms_norm_eps=1e-6,
    ),
}


def get_config(name: str, **overrides) -> ModelConfig:
    """Look up a preset by name, optionally overriding fields."""
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg
