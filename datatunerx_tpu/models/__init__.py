from datatunerx_tpu.models.config import ModelConfig, PRESETS, get_config
from datatunerx_tpu.models.llama import (
    init_params,
    forward,
    num_params,
)

__all__ = [
    "ModelConfig",
    "PRESETS",
    "get_config",
    "init_params",
    "forward",
    "num_params",
]
