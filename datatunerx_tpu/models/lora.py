"""LoRA: low-rank adapters as a separate param collection.

Replaces the reference's PEFT `get_peft_model` wrapping (reference
cmd/tuning/train.py:266-280). TPU-native design: adapters live in their own
pytree mirroring `params["layers"]` with stacked [L, ...] leaves, so

- the optimizer state covers ONLY adapter params (the base stays frozen with no
  Adam moments — the memory win that makes LoRA cheap),
- `forward(..., lora=(lora_params, scaling))` applies h·W + (h·A)·B·scale inside
  each projection (fusable by XLA; Pallas fused kernel in ops/lora_matmul.py),
- `merge_lora` folds adapters into base kernels for export/serving, matching
  PEFT's `merge_and_unload` semantics.

Init matches PEFT (reference peft 0.5.0): A ~ kaiming-uniform, B = 0, so the
delta starts at zero. Scaling = lora_alpha / lora_rank. Defaults mirror the
reference CLI: rank 8, alpha 32, dropout 0.1 (reference cmd/tuning/parser.py:138-149);
the controller always passes ``--lora_target q_proj,v_proj`` (reference
internal/controller/finetune/finetune_controller.go:482).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig

# Valid llama-family targets (reference cmd/tuning/parser.py:150-160).
LORA_TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj",
)
DEFAULT_TARGETS = ("q_proj", "v_proj")


def target_dims(cfg: ModelConfig, name: str) -> tuple[int, int]:
    D, F = cfg.hidden_size, cfg.intermediate_size
    return {
        "q_proj": (D, cfg.q_dim),
        "k_proj": (D, cfg.kv_dim),
        "v_proj": (D, cfg.kv_dim),
        "o_proj": (cfg.q_dim, D),
        "gate_proj": (D, F),
        "up_proj": (D, F),
        "down_proj": (F, D),
    }[name]


def lora_scaling(alpha: float, rank: int) -> float:
    return float(alpha) / float(rank)


def init_lora_params(
    cfg: ModelConfig,
    key: jax.Array,
    rank: int = 8,
    targets: Sequence[str] = DEFAULT_TARGETS,
    dtype=jnp.float32,
):
    for t in targets:
        if t not in LORA_TARGETS:
            raise ValueError(f"invalid lora target {t!r}; choices: {LORA_TARGETS}")
    L = cfg.num_layers
    layers = {}
    for i, t in enumerate(sorted(set(targets))):
        d_in, d_out = target_dims(cfg, t)
        # kaiming-uniform(a=sqrt(5)) over fan_in, like torch Linear / peft LoRA A:
        # bound = sqrt(6 / ((1 + a^2) * fan_in)) = 1 / sqrt(fan_in)
        bound = 1.0 / math.sqrt(d_in)
        a = jax.random.uniform(
            jax.random.fold_in(key, i), (L, d_in, rank), jnp.float32, -bound, bound
        ).astype(dtype)
        layers[t] = {"a": a, "b": jnp.zeros((L, rank, d_out), dtype)}
    return {"layers": layers}


def num_lora_params(lora_params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(lora_params))


def merge_lora(params, lora_params, scaling: float):
    """Fold adapters into base kernels: W' = W + A·B·scaling (per layer)."""
    layers = dict(params["layers"])
    for t, ab in lora_params["layers"].items():
        delta = jnp.einsum(
            "lir,lro->lio",
            ab["a"].astype(jnp.float32),
            ab["b"].astype(jnp.float32),
        ) * scaling
        proj = dict(layers[t])
        proj["kernel"] = (proj["kernel"].astype(jnp.float32) + delta).astype(
            layers[t]["kernel"].dtype
        )
        layers[t] = proj
    out = dict(params)
    out["layers"] = layers
    return out
