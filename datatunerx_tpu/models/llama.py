"""Llama-family decoder (Llama-2, Mistral, Qwen1.5) as a functional JAX model.

TPU-first design decisions (vs the reference's HF-transformers torch path,
reference cmd/tuning/train.py:236-242):

- **Stacked-layer params + `lax.scan`**: all L transformer blocks share one set
  of leaf arrays with a leading layer axis. One compiled block, O(1) HLO size in
  depth, and GSPMD shards every layer identically.
- **Functional**: params are a plain pytree; `forward` is pure. `pjit`/remat/
  `shard_map` compose without framework hooks.
- **bf16 by default on TPU**, f32 norms/softmax; remat ("gradient checkpointing",
  reference cmd/tuning/train.py:205) is a config knob applied to the scan body.
- **Optional KV cache** threaded through the same forward for serving.
- **Optional LoRA tree** applied inside each projection so one code path covers
  base, LoRA train, and merged inference (reference PEFT usage train.py:266-280).

Param tree (HF-compatible leaf names so weight conversion is mechanical):
  embed_tokens.embedding [V, D]
  layers.{input_layernorm,post_attention_layernorm}.scale [L, D]
  layers.{q,k,v,o}_proj.kernel  [L, in, out] (+ .bias for Qwen q/k/v)
  layers.{gate,up,down}_proj.kernel
  norm.scale [D];  lm_head.kernel [D, V] (absent when tied)
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.ops.attention import (
    attention,
    attention_allow,
    cache_positions_update,
    kv_cache_update,
    kv_cache_width,
    kv_cache_write_paged,
    make_causal_bias,
)
from datatunerx_tpu.ops.paged_attention import POS_SENTINEL
from datatunerx_tpu.ops.rope import apply_rope, rope_cos_sin

Params = Any  # nested dict pytree


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * scale.astype(jnp.float32)).astype(dtype)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    keys = jax.random.split(key, 16)
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def dense(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "input_layernorm": {"scale": jnp.ones((L, D), dtype)},
        "post_attention_layernorm": {"scale": jnp.ones((L, D), dtype)},
        "q_proj": {"kernel": dense(keys[0], (L, D, cfg.q_dim))},
        "k_proj": {"kernel": dense(keys[1], (L, D, cfg.kv_dim))},
        "v_proj": {"kernel": dense(keys[2], (L, D, cfg.kv_dim))},
        "o_proj": {"kernel": dense(keys[3], (L, cfg.q_dim, D))},
        "gate_proj": {"kernel": dense(keys[4], (L, D, F))},
        "up_proj": {"kernel": dense(keys[5], (L, D, F))},
        "down_proj": {"kernel": dense(keys[6], (L, F, D))},
    }
    if cfg.attention_bias:
        layers["q_proj"]["bias"] = jnp.zeros((L, cfg.q_dim), dtype)
        layers["k_proj"]["bias"] = jnp.zeros((L, cfg.kv_dim), dtype)
        layers["v_proj"]["bias"] = jnp.zeros((L, cfg.kv_dim), dtype)
    params = {
        "embed_tokens": {"embedding": dense(keys[7], (cfg.vocab_size, D))},
        "layers": layers,
        "norm": {"scale": jnp.ones((D,), dtype)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense(keys[8], (D, cfg.vocab_size))}
    return params


def num_params(params: Params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def _proj(h, p, lora_p, lora_scale, drop_key=None, drop_rate=0.0,
          quant_mode=None, dims=None, use_pallas=False, lora_idx=None):
    """Dense projection with optional LoRA delta: h W + drop(h) A B * scale.

    The base weight is either a full-precision kernel or a quantized collection
    (ops/quant.py) — QLoRA = quantized frozen base + full-precision adapters
    (reference bnb int4/int8 + peft, cmd/tuning/train.py:224-280).
    LoRA dropout applies to the adapter branch input only, matching peft's
    ``lora_dropout`` (reference cmd/tuning/parser.py:146-149, default 0.1).

    Multi-adapter serving: with ``lora_idx`` ([B] int32), lora_p leaves are
    STACKED over adapters ([E, d_in, r]/[E, r, d_out], per layer) and
    ``lora_scale`` is a vector [E]; each batch row applies its own adapter —
    one decode program serves mixed-adapter batches (no per-adapter merge).
    """
    if "quant" in p:
        from datatunerx_tpu.ops.quant import quantized_matmul

        out = quantized_matmul(h, p["quant"], quant_mode, dims,
                               use_pallas=use_pallas)
    else:
        out = h @ p["kernel"].astype(h.dtype)
    if "bias" in p:
        out = out + p["bias"].astype(h.dtype)
    if lora_p is not None:
        a = lora_p["a"].astype(h.dtype)
        b = lora_p["b"].astype(h.dtype)
        hl = h
        if drop_key is not None and drop_rate > 0.0:
            keep = jax.random.bernoulli(drop_key, 1.0 - drop_rate, h.shape)
            hl = jnp.where(keep, h / (1.0 - drop_rate), 0.0).astype(h.dtype)
        if lora_idx is not None:
            a_sel = a[lora_idx]  # [B, d_in, r]
            b_sel = b[lora_idx]  # [B, r, d_out]
            scale = jnp.asarray(lora_scale, h.dtype)[lora_idx][:, None, None]
            delta = jnp.einsum("btd,bdr->btr", hl, a_sel)
            out = out + jnp.einsum("btr,bro->bto", delta, b_sel) * scale
        else:
            out = out + ((hl @ a) @ b) * jnp.asarray(lora_scale, h.dtype)
    return out


# POS_SENTINEL (imported above) marks invalid/pad cache slots: the causal
# check kv_pos <= q_pos masks them with no separate validity plumbing. The
# paged block-pool cache (ops/paged_attention.py ``init_paged_cache``) is the
# elastic alternative to this dense layout; both satisfy the same
# ops/attention.py cache interface.


def init_cache(cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16,
               per_slot: bool = False, quantize: Optional[str] = None):
    """KV cache. ``per_slot=True`` gives each batch row its own write cursor
    (``len`` is [batch]) — continuous batching needs rows at different depths
    in one decode program (serving/batched_engine.py).

    ``quantize="int8"`` stores k/v as int8 with a per-vector (over head_dim)
    scale — half the cache HBM of bf16, so double the slot × context budget
    for serving; dequantized on read inside the same program."""
    L = cfg.num_layers
    shape = (L, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    cache = {
        "len": (jnp.zeros((batch,), jnp.int32) if per_slot
                else jnp.zeros((), jnp.int32)),
        # rope position of each written slot (slots ≠ positions under
        # left-padded prefill); sentinel = unwritten or pad
        "pos": jnp.full((batch, max_len), POS_SENTINEL, jnp.int32),
    }
    if quantize == "int8":
        cache["k"] = jnp.zeros(shape, jnp.int8)
        cache["v"] = jnp.zeros(shape, jnp.int8)
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    elif quantize:
        raise ValueError(f"unsupported cache quantization {quantize!r}")
    else:
        cache["k"] = jnp.zeros(shape, dtype)
        cache["v"] = jnp.zeros(shape, dtype)
    return cache


def lm_logits(params: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Project final-norm hidden states onto the vocabulary ([..., D] →
    [..., V] float32). Exposed so rm/ppo can project only the response
    window instead of paying the lm_head matmul for every prompt position."""
    if cfg.tie_word_embeddings or "lm_head" not in params:
        logits = x @ params["embed_tokens"]["embedding"].astype(x.dtype).T
    else:
        logits = x @ params["lm_head"]["kernel"].astype(x.dtype)
    return logits.astype(jnp.float32)


def forward(
    params: Params,
    tokens: jnp.ndarray,  # [B, T] int32
    cfg: ModelConfig,
    *,
    positions: Optional[jnp.ndarray] = None,  # [B, T]
    attention_mask: Optional[jnp.ndarray] = None,  # [B, T] 1=valid, 0=pad
    segment_ids: Optional[jnp.ndarray] = None,  # [B, T] for packed sequences
    cache: Optional[dict] = None,
    lora: Optional[tuple[Params, float]] = None,
    lora_adapter_idx: Optional[jnp.ndarray] = None,  # [B] — stacked adapters
    compute_dtype=None,
    lora_dropout: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    neftune_alpha: float = 0.0,
    return_hidden: bool = False,
    skip_logits: bool = False,
    window_mask: Optional[jnp.ndarray] = None,  # [B, T, WN] bool — see below
    window_start: Optional[jnp.ndarray] = None,  # [B] linear window start
):
    """Returns (logits [B, T, V] float32, new_cache | None); with
    ``return_hidden`` also the final-norm hidden states [B, T, D].
    ``skip_logits`` (requires return_hidden) returns logits=None — value-head
    consumers (rm/ppo) skip the [T, V] lm_head matmul entirely and project
    only the positions they need via ``lm_logits``.

    ``window_mask``/``window_start`` (tree-draft speculative verification,
    serving/speculative.py): an extra attendability mask over the WN cache
    lanes starting at ``window_start`` (this step's own writes — tree
    branches sharing rope positions attend only their own root-to-leaf
    path). ``window_start`` defaults to the pre-step ``cache["len"]``.
    Outside the window, masking is untouched; ``None`` is byte-identical
    to before the parameter existed."""
    if skip_logits and not return_hidden:
        raise ValueError("skip_logits without return_hidden returns nothing")
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    x = params["embed_tokens"]["embedding"][tokens]
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
    if neftune_alpha > 0.0 and dropout_rng is not None:
        # NEFTune (reference cmd/tuning/parser.py:190-193): uniform noise on the
        # embedding output, magnitude alpha / sqrt(T * D), training only.
        mag = neftune_alpha / jnp.sqrt(jnp.asarray(T * x.shape[-1], jnp.float32))
        noise = jax.random.uniform(
            jax.random.fold_in(dropout_rng, 0x4EF), x.shape, jnp.float32, -1.0, 1.0
        )
        x = x + (noise * mag).astype(x.dtype)

    seq_len = T if cache is None else kv_cache_width(cache)
    cos, sin = rope_cos_sin(
        positions,
        cfg.head_dim,
        theta=cfg.rope_theta,
        scaling_type=cfg.rope_scaling_type,
        scaling_factor=cfg.rope_scaling_factor,
        max_seq_len=cfg.max_seq_len,
        seq_len=seq_len,
    )

    # Pallas in-place decode: single-token steps over a paged cache read the
    # K/V blocks through the block table inside the kernel — no gathered
    # [B, W, KV, d] view, no [B, 1, T, W] bias tensor. Multi-token steps
    # over a paged cache (chunked-prefill chunks, spec verify-k columns,
    # tree-verify windows) ride the multi-token variant, which consumes the
    # oracle's own attendability tensor as a mask operand. Everything else
    # (prefill into dense caches, sliding window, packed segments) keeps
    # the gather path, which doubles as the kernels' parity oracle.
    _paged_cfg = (
        cache is not None
        and "block_tables" in cache
        and getattr(cfg, "paged_kernel", False)
        and cfg.sliding_window is None
    )
    paged_kernel = _paged_cfg and T == 1 and window_mask is None
    paged_kernel_mt = (_paged_cfg and not paged_kernel
                       and segment_ids is None)
    if window_mask is not None and window_start is None:
        if cache is None:
            raise ValueError("window_mask without a cache needs window_start")
        window_start = jnp.broadcast_to(cache["len"], (B,))
    if cache is None:
        kv_positions = positions
        kv_valid = attention_mask.astype(bool) if attention_mask is not None else None
        kv_seg = segment_ids
        cache_pos = None
    else:
        # record each new slot's rope position; pads (attention_mask 0) get
        # the sentinel so the causal check masks them everywhere. The paged
        # cache returns the gathered per-slot linear view as kv_positions
        # (or None on the kernel path, which masks the pos POOL in place).
        cache_pos, kv_positions = cache_positions_update(
            cache, positions, attention_mask, gather=not paged_kernel)
        kv_valid = None  # sentinel positions handle both unwritten and pads
        kv_seg = None
    # flash/ring kernels skip the [B, T, S] bias entirely (building it would
    # defeat their O(T) memory win). Flash handles causal + packed segments
    # in-kernel; ring is causal-only. Cache decode and sliding window need the
    # biased path.
    _flash_ok = (
        cfg.attention_impl in ("flash", "ring")
        and cache is None
        and cfg.sliding_window is None
        and (cfg.attention_impl != "ring" or segment_ids is None)
        and (cfg.attention_impl != "flash" or T % 128 == 0 or T < 128)
    )
    allow = None
    if _flash_ok or paged_kernel:
        bias = None
    elif paged_kernel_mt:
        # the oracle's boolean, handed to the kernel instead of a bias —
        # mask parity with the gather path holds by construction
        bias = None
        allow = attention_allow(
            positions,
            kv_positions,
            kv_valid,
            window_mask=window_mask,
            window_start=window_start,
        )
    else:
        bias = make_causal_bias(
            positions,
            kv_positions,
            kv_valid,
            sliding_window=cfg.sliding_window,
            q_segment_ids=segment_ids,
            kv_segment_ids=kv_seg,
            window_mask=window_mask,
            window_start=window_start,
        )

    lora_layers, lora_scale = (None, 0.0)
    if lora is not None:
        lora_params, lora_scale = lora
        lora_layers = lora_params.get("layers", lora_params)

    drop = lora_dropout if (dropout_rng is not None and lora is not None) else 0.0

    # packed segments, sliding window, and cache decode need the biased path
    att_impl = cfg.attention_impl if _flash_ok else (
        "xla" if cfg.attention_impl in ("flash", "ring") else cfg.attention_impl
    )

    def block(x, scanned):
        lp, ll, ck, cv, cks, cvs, layer_idx = scanned
        lget = (lambda name: ll.get(name)) if ll else (lambda name: None)
        if drop > 0.0:
            lkey = jax.random.fold_in(dropout_rng, layer_idx)
            kget = lambda j: jax.random.fold_in(lkey, j)  # noqa: E731
        else:
            kget = lambda j: None  # noqa: E731

        qm, qp = cfg.quantization, cfg.quant_impl == "pallas"
        D, F = cfg.hidden_size, cfg.intermediate_size

        h = rms_norm(x, lp["input_layernorm"]["scale"], cfg.rms_norm_eps)
        q = _proj(h, lp["q_proj"], lget("q_proj"), lora_scale, kget(0), drop,
                  qm, (D, cfg.q_dim), qp, lora_adapter_idx)
        k = _proj(h, lp["k_proj"], lget("k_proj"), lora_scale, kget(1), drop,
                  qm, (D, cfg.kv_dim), qp, lora_adapter_idx)
        v = _proj(h, lp["v_proj"], lget("v_proj"), lora_scale, kget(2), drop,
                  qm, (D, cfg.kv_dim), qp, lora_adapter_idx)
        q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
        k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        if ck is not None and paged_kernel:
            # in-place decode: scatter the token's K/V into its blocks, then
            # the Pallas kernel reads them back through the block table —
            # the [B, W, KV, d] gathered view never materializes
            from datatunerx_tpu.ops.pallas_paged_attention import (
                paged_attention_decode_step,
            )

            ck, cv, cks, cvs = kv_cache_write_paged(
                cache, ck, cv, cks, cvs, k, v)
            attn = paged_attention_decode_step(
                q, ck, cv, cks, cvs, cache, cache_pos, positions)
        elif ck is not None and paged_kernel_mt:
            # multi-token in-place: same scatter-then-read-through-the-table
            # scheme with the precomputed attendability operand standing in
            # for the oracle's bias
            from datatunerx_tpu.ops.pallas_paged_attention import (
                paged_attention_multitoken_step,
            )

            ck, cv, cks, cvs = kv_cache_write_paged(
                cache, ck, cv, cks, cvs, k, v)
            attn = paged_attention_multitoken_step(
                q, ck, cv, cks, cvs, cache, allow)
        else:
            if ck is not None:
                # dense (scalar/per-slot cursor) or paged (block-table)
                # write + full-width read via the shared cache interface
                ck, cv, cks, cvs, k_att, v_att = kv_cache_update(
                    cache, ck, cv, cks, cvs, k, v)
            else:
                k_att, v_att = k, v

            attn = attention(
                q, k_att, v_att, bias, impl=att_impl,
                segment_ids=segment_ids if att_impl == "flash" else None)
        attn = attn.reshape(B, T, cfg.q_dim)
        x = x + _proj(attn, lp["o_proj"], lget("o_proj"), lora_scale, kget(3),
                      drop, qm, (cfg.q_dim, D), qp, lora_adapter_idx)

        h = rms_norm(x, lp["post_attention_layernorm"]["scale"], cfg.rms_norm_eps)
        gate = _proj(h, lp["gate_proj"], lget("gate_proj"), lora_scale, kget(4),
                     drop, qm, (D, F), qp, lora_adapter_idx)
        up = _proj(h, lp["up_proj"], lget("up_proj"), lora_scale, kget(5),
                   drop, qm, (D, F), qp, lora_adapter_idx)
        mlp = _proj(
            jax.nn.silu(gate) * up, lp["down_proj"], lget("down_proj"),
            lora_scale, kget(6), drop, qm, (F, D), qp, lora_adapter_idx,
        )
        x = x + mlp
        return x, (ck, cv, cks, cvs)

    if cfg.remat == "full":
        block = jax.checkpoint(block)
    elif cfg.remat == "dots":
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        )

    quant_kv = cache is not None and "k_scale" in cache
    xs = (
        params["layers"],
        lora_layers,
        cache["k"] if cache is not None else None,
        cache["v"] if cache is not None else None,
        cache["k_scale"] if quant_kv else None,
        cache["v_scale"] if quant_kv else None,
        jnp.arange(cfg.num_layers, dtype=jnp.int32),
    )
    # DTX_SCAN_UNROLL: cost-analysis instrumentation (scripts/aot_certify.py).
    # XLA's cost_analysis counts a while-loop body ONCE regardless of trip
    # count, so a compiled train step under-reports flops/bytes by ~L×;
    # compiling at unroll=1 vs unroll=2 and differencing recovers the exact
    # per-layer cost. Default 1 = production behavior, byte-identical program.
    _unroll = int(os.environ.get("DTX_SCAN_UNROLL", "1"))
    x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(block, x, xs,
                                                     unroll=_unroll)

    x = rms_norm(x, params["norm"]["scale"], cfg.rms_norm_eps)
    logits = None if skip_logits else lm_logits(params, x, cfg)

    new_cache = None
    if cache is not None:
        new_cache = {"k": new_k, "v": new_v, "len": cache["len"] + T,
                     "pos": cache_pos}
        if quant_kv:
            new_cache["k_scale"] = new_ks
            new_cache["v_scale"] = new_vs
        if "block_tables" in cache:
            new_cache["block_tables"] = cache["block_tables"]
    if return_hidden:
        # final-norm hidden states, for value heads (reward modelling)
        return logits, new_cache, x
    return logits, new_cache
