"""HF-transformers ⇄ datatunerx-tpu weight conversion.

The reference loads base models directly from HF checkpoints
(reference cmd/tuning/train.py:236-242, ``--model_name_or_path``). Our param tree
keeps HF leaf names, so conversion is: stack the per-layer tensors along a new
leading layer axis and transpose torch ``Linear`` [out, in] kernels to [in, out].

Works from a plain ``state_dict``-like mapping of numpy arrays (no torch
dependency in the core path; tests use torch-cpu to produce the dict).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from datatunerx_tpu.models.config import ModelConfig

_LAYER_KERNELS = [
    ("self_attn.q_proj", "q_proj"),
    ("self_attn.k_proj", "k_proj"),
    ("self_attn.v_proj", "v_proj"),
    ("self_attn.o_proj", "o_proj"),
    ("mlp.gate_proj", "gate_proj"),
    ("mlp.up_proj", "up_proj"),
    ("mlp.down_proj", "down_proj"),
]
_LAYER_NORMS = [
    ("input_layernorm", "input_layernorm"),
    ("post_attention_layernorm", "post_attention_layernorm"),
]


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().to("cpu").float().numpy()
    return np.asarray(x, dtype=np.float32)


def convert_hf_state_dict(
    sd: Mapping[str, "np.ndarray"], cfg: ModelConfig, dtype=np.float32
):
    """Convert an HF llama/mistral/qwen2 state_dict to our stacked param tree."""
    L = cfg.num_layers
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""

    def get(k):
        return _np(sd[prefix + k])

    layers: dict = {}
    for hf_name, our_name in _LAYER_KERNELS:
        kernels = np.stack(
            [get(f"layers.{i}.{hf_name}.weight").T for i in range(L)]
        ).astype(dtype)
        layers[our_name] = {"kernel": kernels}
        bias_key = f"{prefix}layers.0.{hf_name}.bias"
        if bias_key in sd:
            layers[our_name]["bias"] = np.stack(
                [_np(sd[f"{prefix}layers.{i}.{hf_name}.bias"]) for i in range(L)]
            ).astype(dtype)
    for hf_name, our_name in _LAYER_NORMS:
        layers[our_name] = {
            "scale": np.stack(
                [get(f"layers.{i}.{hf_name}.weight") for i in range(L)]
            ).astype(dtype)
        }

    params = {
        "embed_tokens": {"embedding": get("embed_tokens.weight").astype(dtype)},
        "layers": layers,
        "norm": {"scale": get("norm.weight").astype(dtype)},
    }
    if "lm_head.weight" in sd and not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T.astype(dtype)}
    return params


def export_hf_state_dict(params, cfg: ModelConfig) -> dict:
    """Inverse of convert_hf_state_dict (numpy arrays, HF key names)."""
    out = {}
    out["model.embed_tokens.weight"] = np.asarray(
        params["embed_tokens"]["embedding"], np.float32
    )
    layers = params["layers"]
    for hf_name, our_name in _LAYER_KERNELS:
        kern = np.asarray(layers[our_name]["kernel"], np.float32)
        for i in range(cfg.num_layers):
            out[f"model.layers.{i}.{hf_name}.weight"] = kern[i].T
        if "bias" in layers[our_name]:
            bias = np.asarray(layers[our_name]["bias"], np.float32)
            for i in range(cfg.num_layers):
                out[f"model.layers.{i}.{hf_name}.bias"] = bias[i]
    for hf_name, our_name in _LAYER_NORMS:
        scale = np.asarray(layers[our_name]["scale"], np.float32)
        for i in range(cfg.num_layers):
            out[f"model.layers.{i}.{hf_name}.weight"] = scale[i]
    out["model.norm.weight"] = np.asarray(params["norm"]["scale"], np.float32)
    if "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"]["kernel"], np.float32).T
    return out


def config_from_hf(hf_cfg) -> ModelConfig:
    """Build a ModelConfig from an HF PretrainedConfig (llama/mistral/qwen2)."""
    return ModelConfig(
        name=getattr(hf_cfg, "model_type", "llama"),
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        rms_norm_eps=hf_cfg.rms_norm_eps,
        tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        attention_bias=getattr(hf_cfg, "model_type", "") == "qwen2"
        or getattr(hf_cfg, "attention_bias", False),
        sliding_window=getattr(hf_cfg, "sliding_window", None)
        if getattr(hf_cfg, "model_type", "") == "mistral"
        else None,
    )
