"""Dependency-free byte-level tokenizer with the HF duck-type surface the
template/data layer needs. Used for preset (random-init) models, CPU smoke
runs, and tests — real checkpoints use HF AutoTokenizer."""

from __future__ import annotations

from typing import List


class SimpleTokenizer:
    """Byte-level: token id = 10 + byte for vocab compactness; ids < 10 and a
    special-token region (3000+) are reserved."""

    def __init__(self, add_bos_token: bool = True):
        self.bos_token_id = 1
        self.eos_token_id = 2
        self.bos_token = "<s>"
        self.eos_token = "</s>"
        self.pad_token = None
        self.pad_token_id = None
        self.unk_token_id = 0
        self.add_bos_token = add_bos_token
        self._special = {"<s>": 1, "</s>": 2}
        self._special_rev = {1: "<s>", 2: "</s>"}

    @property
    def vocab_size(self) -> int:
        return 3000 + len(self._special)

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids = [10 + b for b in text.encode("utf-8")]
        if add_special_tokens and self.add_bos_token:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        out = bytearray()
        for i in ids:
            i = int(i)
            if 10 <= i < 266:
                out.append(i - 10)
            elif not skip_special_tokens and i in self._special_rev:
                out.extend(self._special_rev[i].encode())
        return out.decode("utf-8", errors="replace")

    def convert_tokens_to_ids(self, token: str) -> int:
        if token not in self._special:
            idx = 3000 + len(self._special)
            self._special[token] = idx
            self._special_rev[idx] = token
        return self._special[token]

    def add_special_tokens(self, mapping, replace_additional_special_tokens=False):
        for tok in mapping.get("additional_special_tokens", []):
            self.convert_tokens_to_ids(tok)

    def __setattr__(self, k, v):
        super().__setattr__(k, v)
        if k == "pad_token" and v is not None:
            super().__setattr__("pad_token_id", self._special.get(v, self.eos_token_id))
