"""Model + tokenizer resolution for the trainer CLI.

``--model_name_or_path`` accepts (reference loads HF checkpoints directly,
cmd/tuning/train.py:236-242):

- ``preset:<name>``      — random-init from a ModelConfig preset with the
                           byte-level SimpleTokenizer (smoke/dev/e2e tests);
- a directory with our own ``model.npz`` + ``config.json`` export
                           (training/checkpoint.py export_merged_model);
- an HF checkpoint dir   — config via config_from_hf, weights via
                           AutoModelForCausalLM (torch CPU), tokenizer via
                           AutoTokenizer.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Tuple

import jax
import numpy as np

from datatunerx_tpu.models.config import ModelConfig, get_config
from datatunerx_tpu.models.llama import init_params
from datatunerx_tpu.utils.hf_convert import config_from_hf, convert_hf_state_dict
from datatunerx_tpu.utils.simple_tokenizer import SimpleTokenizer


def load_model_and_tokenizer(
    path_or_preset: str,
    dtype=np.float32,
    seed: int = 0,
    config_overrides: Optional[dict] = None,
) -> Tuple[ModelConfig, dict, object]:
    overrides = config_overrides or {}
    if path_or_preset.startswith("preset:"):
        cfg = get_config(path_or_preset.split(":", 1)[1], **overrides)
        tok = SimpleTokenizer()
        # byte-level tokenizer needs vocab >= 3000+specials
        if cfg.vocab_size < 3100:
            cfg = dataclasses.replace(cfg, vocab_size=3104)
        params = init_params(cfg, jax.random.PRNGKey(seed), dtype=dtype)
        return cfg, params, tok

    if not os.path.isdir(path_or_preset):
        raise FileNotFoundError(f"model path {path_or_preset!r} does not exist")

    npz = os.path.join(path_or_preset, "model.npz")
    if os.path.exists(npz):
        with open(os.path.join(path_or_preset, "config.json")) as f:
            raw = json.load(f)
        field_names = {f.name for f in dataclasses.fields(ModelConfig)}
        raw = {k: v for k, v in raw.items() if k in field_names}
        for k in ("head_dim", "sliding_window"):
            if raw.get(k) in ("None", ""):
                raw[k] = None
        raw.update(overrides)
        cfg = ModelConfig(**raw)
        sd = dict(np.load(npz))
        params = convert_hf_state_dict(sd, cfg, dtype=dtype)
        tok = _load_hf_tokenizer(path_or_preset) or SimpleTokenizer()
        return cfg, params, tok

    # HF checkpoint directory
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(path_or_preset)
    cfg = config_from_hf(hf_cfg)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    model = AutoModelForCausalLM.from_pretrained(path_or_preset)
    params = convert_hf_state_dict(model.state_dict(), cfg, dtype=dtype)
    del model
    tok = _load_hf_tokenizer(path_or_preset)
    if tok is None:
        raise FileNotFoundError(f"no tokenizer found under {path_or_preset}")
    return cfg, params, tok


def load_tokenizer(path_or_preset: str):
    """Tokenizer WITHOUT the weights — for components that only need token
    counts (e.g. the gateway's admission estimator). Never initializes params;
    returns None when no tokenizer can be found (callers fall back to a
    chars/token heuristic)."""
    if path_or_preset.startswith("preset:"):
        return SimpleTokenizer()
    if not os.path.isdir(path_or_preset):
        return None
    tok = _load_hf_tokenizer(path_or_preset)
    if tok is None and os.path.exists(
            os.path.join(path_or_preset, "model.npz")):
        # in-repo export format ships without a tokenizer dir: the byte-level
        # SimpleTokenizer is what serving pairs with it
        return SimpleTokenizer()
    return tok


def _load_hf_tokenizer(path: str):
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(path)
    except Exception:
        return None
