"""Shared prompt-preparation for KV-cache generation (serving engine +
in-training generative eval).

Left-pad to a compile bucket with the pads attention-masked; real tokens keep
rope positions 0..n-1 regardless of cache slot (models/llama.py records
per-slot positions). Budgets are clamped so cache width never exceeds
max_seq_len — oversized caches would wrongly trigger dynamic-NTK rope
inflation (ops/rope.py reads the cache width as seq_len).
"""

from __future__ import annotations

from typing import List, Tuple

DECODE_BUCKET = 64


def prepare_prompt(
    prompt_ids: List[int],
    eos_id: int,
    max_seq_len: int,
    max_new_tokens: int,
    bucket: int = DECODE_BUCKET,
) -> Tuple[List[int], List[int], List[int], int, int, int]:
    """Returns (ids, mask, positions, plen, n_prompt, max_new_clamped, buf).

    buf is the static decode-buffer length (cache width = plen + buf)."""
    max_new = max(1, min(max_new_tokens, max_seq_len - bucket))
    # floor the kept-prompt cap to a bucket multiple so plen is ALWAYS one:
    # chunked prefill splits plen into bucket-multiple chunks, so an off-bucket
    # plen (any off-bucket max_new) would compile a fresh tail-chunk program
    # per distinct remainder (`or keep`: sub-bucket max_seq_len keeps the
    # un-floored cap rather than rounding to zero)
    keep = max_seq_len - max_new
    keep = keep // bucket * bucket or keep
    prompt_ids = list(prompt_ids)[-keep:]
    if not prompt_ids:
        # empty prompt: seed with a single (unmasked) eos — an all-masked
        # prefill row would softmax to NaN
        prompt_ids = [eos_id]
    plen = min(-(-len(prompt_ids) // bucket) * bucket, keep)
    prompt_ids = prompt_ids[-plen:]
    n = len(prompt_ids)
    pad = plen - n
    ids = [eos_id] * pad + prompt_ids
    mask = [0] * pad + [1] * n
    positions = [0] * pad + list(range(n))
    # clamp the decode budget so plen + buffer <= max_seq_len
    buf = min(-(-max_new // bucket) * bucket, max_seq_len - plen)
    max_new = min(max_new, buf)
    return ids, mask, positions, plen, n, max_new, buf
