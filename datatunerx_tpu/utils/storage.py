"""Object-storage plane: URI-aware file IO for datasets, manifests, exports.

The reference ingests S3 datasets via Ray Data (reference cmd/tuning/
train.py:339) and persists checkpoints under an S3 storage_path
(train.py:369-376; S3 env config pkg/config/config.go:29-55). TPU-native
equivalent: every dataset/manifest/storage path may be a plain local path or
an fsspec URI (``gs://``, ``s3://``, ``memory://``, ``file://`` …) — GKE
deployments point STORAGE_PATH at a bucket; tests use ``memory://``.

Orbax checkpoints go through tensorstore, which speaks ``gs://`` natively, so
checkpoint directories pass through unchanged; everything else funnels through
these helpers.
"""

from __future__ import annotations

import os
import posixpath
from typing import List

_URI_MARK = "://"


def is_uri(path: str) -> bool:
    return _URI_MARK in str(path)


def join(base: str, *parts: str) -> str:
    """os.path.join for local paths, posix join for URIs (so Windows-style
    separators can never corrupt an object key)."""
    if is_uri(base):
        return posixpath.join(base, *parts)
    return os.path.join(base, *parts)


def _storage_options(path: str) -> dict:
    from datatunerx_tpu.operator.config import object_store_options

    return object_store_options(str(path))


def _fs(path: str):
    import fsspec

    fs, _, _ = fsspec.get_fs_token_paths(
        path, storage_options=_storage_options(path)
    )
    return fs


def exists(path: str) -> bool:
    if not is_uri(path):
        return os.path.exists(path)
    return _fs(path).exists(path)


def makedirs(path: str) -> None:
    if not is_uri(path):
        os.makedirs(path, exist_ok=True)
        return
    _fs(path).makedirs(path, exist_ok=True)


def open_uri(path: str, mode: str = "r"):
    """Open a local path or URI for reading/writing."""
    if not is_uri(path):
        return open(path, mode, newline="" if "r" in mode and "b" not in mode else None)
    import fsspec

    return fsspec.open(path, mode, **_storage_options(path)).open()


def read_text(path: str) -> str:
    with open_uri(path, "r") as f:
        return f.read()


def write_text(path: str, content: str) -> None:
    parent = posixpath.dirname(path) if is_uri(path) else os.path.dirname(path)
    if parent:
        makedirs(parent)
    with open_uri(path, "w") as f:
        f.write(content)


def listdir(path: str) -> List[str]:
    if not is_uri(path):
        return sorted(os.listdir(path))
    fs = _fs(path)
    return sorted(posixpath.basename(p.rstrip("/")) for p in fs.ls(path))
