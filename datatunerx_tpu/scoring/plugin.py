"""Scoring plugins: the Scoring CR's ``plugin{loadPlugin, name, parameters}``
contract (reference pkg/util/generate/generate.go:343-358).

A plugin is a Python entrypoint ``module:function`` (or a registered name)
called as ``fn(inference_url, parameters) -> str | float`` returning the score.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_plugin(name: str, fn: Callable) -> None:
    _REGISTRY[name] = fn


def resolve_plugin(name: str) -> Callable:
    if name in _REGISTRY:
        return _REGISTRY[name]
    if ":" in name:
        module, _, attr = name.partition(":")
        mod = importlib.import_module(module)
        return getattr(mod, attr)
    raise KeyError(
        f"scoring plugin {name!r} not registered and not a module:function path"
    )


def run_plugin(name: str, inference_url: str, parameters) -> str:
    fn = resolve_plugin(name)
    result = fn(inference_url, parameters)
    return str(result)
