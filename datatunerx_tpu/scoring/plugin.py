"""Scoring plugins: the Scoring CR's ``plugin{loadPlugin, name, parameters}``
contract (reference pkg/util/generate/generate.go:343-358).

A plugin is a Python entrypoint ``module:function`` (or a registered name)
called as ``fn(inference_url, parameters) -> str | float`` returning the score.
"""

from __future__ import annotations

import importlib
import os
from typing import Callable, Dict, Tuple

_REGISTRY: Dict[str, Callable] = {}

# ``module:function`` plugin paths execute arbitrary code in the OPERATOR
# process, and any CR author can set them — so they are gated behind an
# operator-side allowlist of module prefixes. Empty by default: only
# explicitly-registered plugins work unless the operator opts in via
# DTX_SCORING_PLUGIN_PREFIXES (comma-separated, e.g. "mycompany.scoring.").
PLUGIN_PREFIX_ENV = "DTX_SCORING_PLUGIN_PREFIXES"


def _allowed_prefixes() -> Tuple[str, ...]:
    raw = os.environ.get(PLUGIN_PREFIX_ENV, "")
    return tuple(p.strip() for p in raw.split(",") if p.strip())


def register_plugin(name: str, fn: Callable) -> None:
    _REGISTRY[name] = fn


def resolve_plugin(name: str) -> Callable:
    if name in _REGISTRY:
        return _REGISTRY[name]
    if ":" in name:
        module, _, attr = name.partition(":")
        if not any(module.startswith(p) for p in _allowed_prefixes()):
            raise PermissionError(
                f"scoring plugin module {module!r} is not allowlisted; set "
                f"{PLUGIN_PREFIX_ENV} on the operator to permit it"
            )
        mod = importlib.import_module(module)
        return getattr(mod, attr)
    raise KeyError(
        f"scoring plugin {name!r} not registered and not a module:function path"
    )
