"""Text-similarity metrics: ROUGE-1/2/L and BLEU-4, dependency-free.

These are the metric names the reference logs for generative eval
(reference cmd/tuning/callback.py:103-138: rouge-1, rouge-2, rouge-l, bleu-4;
computed there by jieba+nltk+rouge_chinese inside GenEvalSeq2SeqTrainer).
Token-level implementations on whitespace/char tokens — no nltk/jieba.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, List, Sequence


def _tokens(text: str) -> List[str]:
    toks = text.split()
    if not toks and text:  # CJK-ish: fall back to characters
        toks = list(text)
    return toks


def _ngram_counts(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def rouge_n(candidate: str, reference: str, n: int) -> float:
    """F1, matching the reference's rouge_chinese F-score semantics
    (recall-only inflates scores for long generations)."""
    c, r = _ngram_counts(_tokens(candidate), n), _ngram_counts(_tokens(reference), n)
    if not r:
        return 0.0
    overlap = sum((c & r).values())
    if overlap == 0:
        return 0.0
    p = overlap / max(sum(c.values()), 1)
    rec = overlap / max(sum(r.values()), 1)
    return 2 * p * rec / (p + rec)


def _lcs(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_l(candidate: str, reference: str) -> float:
    a, b = _tokens(candidate), _tokens(reference)
    lcs = _lcs(a, b)
    if lcs == 0:
        return 0.0
    p, r = lcs / len(a), lcs / len(b)
    return 2 * p * r / (p + r)


def bleu4(candidate: str, reference: str, zero_unigram_zero: bool = False) -> float:
    """Smoothed BLEU-4. With zero_unigram_zero (the SCORER's mode), zero
    unigram overlap returns 0 — uniform +1 smoothing otherwise scores 1-token
    garbage ~0.5, which corrupts probe-based model scoring. The default keeps
    uniform smoothing for eval-curve continuity (training generative eval)."""
    cand, ref = _tokens(candidate), _tokens(reference)
    if not cand:
        return 0.0
    logs = 0.0
    for n in range(1, 5):
        c, r = _ngram_counts(cand, n), _ngram_counts(ref, n)
        total = max(sum(c.values()), 1)
        overlap = sum((c & r).values())
        if n == 1 and zero_unigram_zero:
            if overlap == 0:
                return 0.0
            logs += math.log(overlap / total)
        else:
            # +1 smoothing (method-1) so short strings don't zero out
            logs += math.log((overlap + 1) / (total + 1))
    bp = 1.0 if len(cand) >= len(ref) else math.exp(1 - len(ref) / max(len(cand), 1))
    return bp * math.exp(logs / 4)


def generation_scores(candidate: str, reference: str,
                      strict_bleu: bool = False) -> Dict[str, float]:
    return {
        "rouge-1": rouge_n(candidate, reference, 1),
        "rouge-2": rouge_n(candidate, reference, 2),
        "rouge-l": rouge_l(candidate, reference),
        "bleu-4": bleu4(candidate, reference, zero_unigram_zero=strict_bleu),
    }
