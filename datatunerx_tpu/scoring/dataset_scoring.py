"""Dataset-driven scoring: evaluate a served model over a real eval split.

The reference's scoring sibling only probes the endpoint; SURVEY.md §2.3 notes
the Dataset CR carries train/validate/test split URIs
(reference internal/controller/finetune/finetune_controller.go:466-470).
Here a Scoring CR may reference that Dataset (``spec.datasetRef``) and the
controller scores the serving endpoint over its test (fallback: validate)
split — two metrics:

- ``generation`` (default): ROUGE-L/BLEU of sampled completions against the
  reference column (the metric family the reference logs,
  cmd/tuning/callback.py:103-138), averaged and scaled 0-100;
- ``perplexity``: the serving ``/perplexity`` endpoint returns the mean
  completion NLL under the model; score = 100·exp(−NLL) — the geometric-mean
  per-token probability as a percentage, so HIGHER is better and experiment
  BestVersion sorting (reference finetuneexperiment_controller.go:199-216)
  keeps working.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional

from datatunerx_tpu.scoring.builtin import query_chat
from datatunerx_tpu.scoring.metrics import generation_scores

DEFAULT_MAX_EXAMPLES = 100


def split_file_from_dataset_spec(dataset_spec: dict) -> Optional[str]:
    """Test split if present, else validate (never train — scoring on the
    training data would reward memorization)."""
    info = ((dataset_spec.get("datasetMetadata") or {})
            .get("datasetInfo") or {})
    for subset in info.get("subsets") or []:
        splits = subset.get("splits") or {}
        for split in ("test", "validate"):
            f = (splits.get(split) or {}).get("file")
            if f:
                return f
    return None


def columns_from_dataset_spec(dataset_spec: dict) -> Optional[Dict[str, str]]:
    info = ((dataset_spec.get("datasetMetadata") or {})
            .get("datasetInfo") or {})
    features = info.get("features") or []
    cols = {f.get("mapTo"): f.get("name") for f in features
            if f.get("mapTo") and f.get("name")}
    return cols or None


def load_eval_records(dataset_spec: dict,
                      max_examples: int = DEFAULT_MAX_EXAMPLES) -> List[dict]:
    """→ [{"prompt": …, "reference": …}] from the dataset's eval split."""
    from datatunerx_tpu.data.loader import CsvDataset
    from datatunerx_tpu.data.preprocess import map_columns

    path = split_file_from_dataset_spec(dataset_spec)
    if not path:
        raise ValueError("dataset has no test/validate split to score against")
    cols = columns_from_dataset_spec(dataset_spec)
    ds = CsvDataset(path, columns=cols)
    out = []
    for rec in ds.records[: max(1, max_examples)]:
        rec = map_columns(rec, cols)
        prompt = rec.get("instruction") or ""
        query = rec.get("query") or ""
        if query:
            prompt = f"{prompt}\n{query}" if prompt else query
        ref = rec.get("response") or ""
        if prompt and ref:
            out.append({"prompt": prompt, "reference": ref})
    if not out:
        raise ValueError("eval split yielded no usable (prompt, reference) rows")
    return out


def query_perplexity(endpoint: str, prompt: str, completion: str,
                     timeout: float = 60.0, model=None) -> dict:
    """POST the serving /perplexity endpoint (serving/server.py)."""
    url = endpoint.rsplit("/chat/completions", 1)[0].rstrip("/") + "/perplexity"
    body = {"prompt": prompt, "completion": completion}
    if model:
        body["model"] = model  # adapter routing, serving/server.py
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def score_dataset(
    inference_url: str,
    dataset_spec: dict,
    metric: str = "generation",
    max_examples: int = DEFAULT_MAX_EXAMPLES,
    timeout: float = 60.0,
    model=None,
) -> Dict:
    """Returns {"score": "NN.N", "details": {…}} over the dataset's eval split."""
    records = load_eval_records(dataset_spec, max_examples=max_examples)
    if metric == "perplexity":
        import math

        total_nll, total_tokens = 0.0, 0
        for r in records:
            resp = query_perplexity(inference_url, r["prompt"], r["reference"],
                                    timeout=timeout, model=model)
            total_nll += float(resp["nll_sum"])
            total_tokens += int(resp["num_tokens"])
        mean_nll = total_nll / max(total_tokens, 1)
        score = 100.0 * math.exp(-mean_nll)
        details = {
            "metric": "perplexity",
            "examples": len(records),
            "perplexity": math.exp(mean_nll),
            "mean_nll": mean_nll,
        }
        return {"score": f"{score:.2f}", "details": details}

    if metric != "generation":
        raise ValueError(f"unknown scoring metric {metric!r}")
    total = 0.0
    agg = {"rouge-1": 0.0, "rouge-2": 0.0, "rouge-l": 0.0, "bleu-4": 0.0}
    for r in records:
        answer = query_chat(inference_url, r["prompt"], timeout=timeout,
                            model=model)
        s = generation_scores(answer, r["reference"], strict_bleu=True)
        total += max(s["rouge-l"], s["bleu-4"])
        for k in agg:
            agg[k] += s[k]
    n = len(records)
    details = {"metric": "generation", "examples": n,
               **{k: round(v / n, 4) for k, v in agg.items()}}
    return {"score": f"{100.0 * total / n:.1f}", "details": details}
