"""Scoring controller: reconciles Scoring CRs by driving the inference
endpoint and writing status.score.

The reference keeps this in a sibling-repo operator and only creates/watches
the CR (SURVEY.md §2.3 Scoring); here it's in-tree so the pipeline is
self-contained. Built-in path uses the probe scorer; plugin path resolves the
named plugin (reference plugin contract, generate.go:343-358).
"""

from __future__ import annotations

import os

from typing import Optional

from datatunerx_tpu.operator.api import Scoring
from datatunerx_tpu.operator.reconciler import Result
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.scoring.builtin import score_endpoint, validate_probes
from datatunerx_tpu.scoring.dataset_scoring import (
    DEFAULT_MAX_EXAMPLES,
    score_dataset,
)
from datatunerx_tpu.scoring.plugin import resolve_plugin

RETRY_S = float(os.environ.get("DTX_SCORING_RETRY_S", "10.0"))


class ScoringController:
    kind = Scoring

    def __init__(self, timeout: float = 120.0):
        self.timeout = timeout

    def reconcile(self, store: ObjectStore, scoring: Scoring) -> Optional[Result]:
        if scoring.metadata.deletion_timestamp:
            return None
        if scoring.status.get("score") is not None:
            return None  # done

        url = scoring.spec.get("inferenceService")
        if not url:
            scoring.status["error"] = "spec.inferenceService is required"
            store.update(scoring)
            return None

        plugin = scoring.spec.get("plugin") or {}
        dataset_ref = scoring.spec.get("datasetRef")
        metric = scoring.spec.get("metric") or "generation"
        # Validate the spec BEFORE any endpoint traffic — this is the only
        # permanent-error branch. Endpoint failures (including a warming
        # server returning a 200 with a non-OpenAI body, which surfaces as
        # JSONDecodeError/KeyError from the response parser) must retry.
        try:
            if plugin.get("loadPlugin"):
                fn = resolve_plugin(plugin.get("name", ""))
            elif dataset_ref:
                if metric not in ("generation", "perplexity"):
                    raise ValueError(f"unknown scoring metric {metric!r}")
                max_examples = int(scoring.spec.get("maxExamples")
                                   or DEFAULT_MAX_EXAMPLES)
                if max_examples <= 0:
                    raise ValueError("maxExamples must be positive")
            else:
                # built-in scorer accepts CR-supplied probes
                # (spec.probes: [{prompt, reference}]); defaults otherwise
                probes = validate_probes(scoring.spec.get("probes"))
        except (KeyError, TypeError, ValueError, PermissionError,
                ImportError, AttributeError) as e:
            # bad spec OR bad-but-allowlisted plugin path — permanent either way
            scoring.status["error"] = f"invalid scoring spec: {e!r}"[:500]
            store.update(scoring)
            return None

        try:
            if plugin.get("loadPlugin"):
                score = str(fn(url, plugin.get("parameters")))
                details = None
            elif dataset_ref:
                from datatunerx_tpu.operator.api import Dataset

                ds = store.try_get(Dataset, dataset_ref,
                                   scoring.metadata.namespace)
                if ds is None:  # may be created later — retry
                    scoring.status["lastError"] = f"Dataset/{dataset_ref} not found"
                    store.update(scoring)
                    return Result(requeue_after=RETRY_S)
                result = score_dataset(url, ds.spec, metric=metric,
                                       max_examples=max_examples,
                                       timeout=self.timeout,
                                       model=scoring.spec.get("model"))
                score, details = result["score"], result["details"]
            else:
                result = score_endpoint(
                    url, probes=probes, timeout=self.timeout,
                    # spec.model: named adapter on a multi-adapter engine —
                    # N Scorings against ONE endpoint compare N checkpoints
                    model=scoring.spec.get("model"))
                score, details = result["score"], result["details"]
        except Exception as e:  # endpoint not ready / transient — retry
            scoring.status["lastError"] = str(e)[:500]
            store.update(scoring)
            return Result(requeue_after=RETRY_S)

        scoring.status["score"] = str(score)
        if details is not None:
            scoring.status["details"] = details
        scoring.status.pop("lastError", None)
        store.update(scoring)
        return None
