"""Built-in scorer: drives the inference service and produces a 0-100 score.

The reference delegates scoring to a sibling-repo operator that POSTs to the
job's ``/chat/completions`` endpoint and writes ``Scoring.Status.Score``
(SURVEY.md §2.3 Scoring, §3.4). This is our in-tree equivalent: a fixed (or
CR-parameterized) probe set is sent to the endpoint; answers are scored with
ROUGE-L/BLEU against references, averaged, and scaled to 0-100. Scores stay
strings end-to-end for API parity (reference quirk, util.go:24-30)."""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional

from datatunerx_tpu.scoring.metrics import generation_scores

DEFAULT_PROBES: List[Dict[str, str]] = [
    {"prompt": "What is the capital of France?", "reference": "Paris"},
    {"prompt": "What is 2 + 2?", "reference": "4"},
    {"prompt": "Name the largest planet in our solar system.", "reference": "Jupiter"},
    {"prompt": "What color is a clear daytime sky?", "reference": "blue"},
    {"prompt": "Who wrote the play Hamlet?", "reference": "William Shakespeare"},
]


def validate_probes(probes) -> Optional[List[Dict[str, str]]]:
    """Shape-check CR-supplied probes before any endpoint traffic, so the
    controller can scope its permanent invalid-spec branch to THIS check
    (endpoint responses that fail to parse must stay retryable — a warming
    server can return a 200 with a non-OpenAI body). None → built-in defaults.
    """
    if probes is None:
        return None
    if not isinstance(probes, list) or not probes:
        raise ValueError("spec.probes must be a non-empty list")
    for i, p in enumerate(probes):
        if (not isinstance(p, dict)
                or not isinstance(p.get("prompt"), str)
                or not isinstance(p.get("reference"), str)):
            raise ValueError(
                f"spec.probes[{i}] must be {{prompt: str, reference: str}}"
            )
    return probes


def query_chat(endpoint: str, prompt: str, timeout: float = 60.0,
               max_tokens: int = 64, model: Optional[str] = None) -> str:
    body = {
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "temperature": 0.0,
    }
    if model:
        # routes to a named LoRA adapter on multi-adapter engines
        # (serving/server.py "model" handling) — side-by-side scoring of N
        # tuned checkpoints through ONE engine (BASELINE row 6)
        body["model"] = model
    req = urllib.request.Request(
        endpoint,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = json.load(resp)
    return payload["choices"][0]["message"]["content"]


def score_endpoint(
    inference_url: str,
    probes: Optional[List[Dict[str, str]]] = None,
    timeout: float = 60.0,
    model: Optional[str] = None,
) -> Dict:
    """Returns {"score": "NN.N", "details": [...]}; raises on transport errors
    so the controller can retry."""
    probes = probes or DEFAULT_PROBES
    details = []
    total = 0.0
    for probe in probes:
        answer = query_chat(inference_url, probe["prompt"], timeout=timeout,
                            model=model)
        s = generation_scores(answer, probe["reference"], strict_bleu=True)
        per = max(s["rouge-l"], s["bleu-4"])
        total += per
        details.append({"prompt": probe["prompt"], "answer": answer, **s})
    final = 100.0 * total / max(len(probes), 1)
    return {"score": f"{final:.1f}", "details": details}
