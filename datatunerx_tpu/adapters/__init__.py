"""Dynamic multi-adapter plane: pooled HBM adapter store + host registry.

Adapters as DATA, not engine config (S-LoRA / Punica): the store owns a
fixed-geometry device pool the decode program indexes per batch row, the
registry loads/evicts/refcounts adapters at runtime — one compiled program
serves any resident set with zero recompiles on load/unload. See
``serving/batched_engine.py`` (adapter_pool mode), the serving server's
``/admin/adapters`` plane, and the gateway's residency-aware routing.
"""

from datatunerx_tpu.adapters.registry import (
    AdapterPinnedError,
    AdapterRegistry,
)
from datatunerx_tpu.adapters.store import (
    AdapterRankError,
    AdapterStore,
    AdapterTargetError,
    adapter_rank,
    hbm_bytes,
    validate_adapter,
)

__all__ = [
    "AdapterPinnedError",
    "AdapterRankError",
    "AdapterRegistry",
    "AdapterStore",
    "AdapterTargetError",
    "adapter_rank",
    "hbm_bytes",
    "validate_adapter",
]
