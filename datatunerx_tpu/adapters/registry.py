"""AdapterRegistry: host-side adapter lifecycle over an AdapterStore.

The registry is the whole-fleet-as-adapter-cache primitive (S-LoRA's
adapter manager, host-rebuilt): it maps adapter *names* to orbax
checkpoints, materialises them into pool slots **on miss** at admission
time, refcounts the slots pinned by in-flight requests, and LRU-evicts
unpinned residents when the pool is full. The serving admin plane
(``POST/DELETE/GET /admin/adapters``) and the engine's admission path are
its only writers; the gateway reads its occupancy through replica stats
and prefers replicas where a request's adapter is already resident.

Loads are ASYNC: ``acquire`` reserves a slot and kicks the checkpoint
read + device insert onto a loader thread, returning None — the engine
FIFO-waits the missing request while DECODE KEEPS TICKING for everyone
else (a cold tenant's load must not spike in-flight streams' TPOT). The
registry lock covers bookkeeping and the (fast) device insert only,
never the checkpoint read; the decode hot path never takes it at all —
it reads the store's atomically-republished ``tree`` snapshot, and
membership/residency reads use lock-free published snapshots.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from datatunerx_tpu.adapters.store import AdapterStore, validate_adapter
from datatunerx_tpu.models.lora import lora_scaling


class AdapterPinnedError(RuntimeError):
    """Unload refused: in-flight requests still decode with this adapter."""


def _default_loader(checkpoint_path: str) -> dict:
    # lazy import: batched_engine imports this package
    from datatunerx_tpu.serving.batched_engine import load_checkpoint_state

    return load_checkpoint_state(checkpoint_path)


class _Entry:
    __slots__ = ("name", "checkpoint", "slot", "refs", "rank", "loads",
                 "loading", "error", "event", "pending_first")

    def __init__(self, name: str, checkpoint: str):
        self.name = name
        self.checkpoint = checkpoint
        self.slot: Optional[int] = None  # device idx 1..P when resident
        self.refs = 0  # active decode slots pinning this adapter
        self.rank: Optional[int] = None  # known after first load
        self.loads = 0
        self.loading = False  # async load in flight (slot reserved)
        self.error: Optional[BaseException] = None  # last load's failure
        self.event: Optional[threading.Event] = None  # set when load ends
        # the first acquire after a load completes is the MISS resolving,
        # not a fresh hit — consume this flag instead of counting a hit
        self.pending_first = False


class AdapterRegistry:
    def __init__(self, store: AdapterStore,
                 loader: Optional[Callable[[str], dict]] = None,
                 load_observer: Optional[Callable[[float], None]] = None,
                 on_load_done: Optional[Callable[[], None]] = None,
                 host_tier=None):
        self.store = store
        self._loader = loader or _default_loader
        # tenancy host-RAM tier (tenancy/host_tier.HostAdapterTier): evicted
        # adapters' host arrays stay cached so evict→reload skips orbax;
        # None (default) = byte-identical pre-tenancy behavior
        self.host_tier = host_tier
        self.host_hits = 0  # loads served from the host tier
        self.orbax_loads = 0  # loads that paid the checkpoint read
        # adapter names immune to LRU eviction (pinned-tier tenants');
        # empty set = pre-tenancy eviction order
        self._pinned_names: set = set()
        # called with each checkpoint load's wall ms (the engine wires the
        # shared-registry dtx_serving_adapter_load_ms histogram here)
        self._load_observer = load_observer
        # called (outside the lock) whenever an async load resolves —
        # success or failure — so the engine can wake its scheduler
        # instead of polling out the FIFO-head's wait
        self._on_load_done = on_load_done
        self._lock = threading.RLock()
        # live async loader threads (pruned on spawn, joined by close());
        # without this a teardown mid-load leaves a worker mutating a
        # dead registry — the SAN002 thread-leak shape
        self._loader_threads: List[threading.Thread] = []
        self._entries: Dict[str, _Entry] = {}
        # resident names in LRU order (front = coldest); pinned entries are
        # skipped by eviction, not reordered out
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._free_slots: List[int] = list(range(1, store.pool_slots + 1))
        self.stats = {"loads": 0, "evictions": 0, "hits": 0, "misses": 0}
        self.load_ms: List[float] = []  # recent load latencies (bounded)
        # lock-free read snapshots, republished on every membership/slot
        # mutation: the lock is deliberately held across checkpoint loads
        # (the designed slow path), and routing stats / submit-time
        # membership checks must not stall behind a multi-second load
        self._resident_snapshot: Dict[str, int] = {}
        self._id_map_snapshot: Dict[str, int] = {"": 0}

    # ----------------------------------------------------------- membership
    def register(self, name: str, checkpoint_path: str) -> dict:
        """Make ``name`` loadable. Idempotent for the same checkpoint;
        re-registering a name under a DIFFERENT checkpoint is refused while
        resident or pinned (unload first) so a tenant's name can never
        silently start serving other weights mid-flight."""
        if not name:
            raise ValueError("adapter name must be non-empty")
        with self._lock:
            ent = self._entries.get(name)
            if ent is not None:
                if ent.checkpoint == checkpoint_path:
                    return self.describe(name)
                if ent.slot is not None or ent.refs or ent.loading:
                    raise AdapterPinnedError(
                        f"adapter {name!r} is resident/pinned/loading under "
                        f"{ent.checkpoint!r}; DELETE it before re-registering"
                        " with a different checkpoint")
                ent.checkpoint = checkpoint_path
                ent.rank = None
                self._publish_locked()
                return self.describe(name)
            self._entries[name] = _Entry(name, checkpoint_path)
            self._publish_locked()
            return self.describe(name)

    def unregister(self, name: str) -> bool:
        """Forget ``name``, evicting its weights if resident. Refuses while
        pinned (AdapterPinnedError → the admin plane answers 409)."""
        with self._lock:
            ent = self._entries.get(name)
            if ent is None:
                return False
            if ent.refs or ent.loading:
                raise AdapterPinnedError(
                    f"adapter {name!r} pinned by {ent.refs} in-flight "
                    "request(s)" + (" (load in progress)" if ent.loading
                                    else ""))
            if ent.slot is not None:
                self._evict_locked(ent)
            del self._entries[name]
            if self.host_tier is not None:
                # a deleted adapter must not resurrect from host RAM
                self.host_tier.drop(name)
            self._publish_locked()
            return True

    def set_pinned(self, names) -> None:
        """Replace the pin-tier adapter set (the tenancy directory's
        pinned tenants' adapters): these names are never chosen as LRU
        eviction victims while resident. Idempotent; an empty set
        restores the pre-tenancy eviction order."""
        with self._lock:
            self._pinned_names = set(names or ())

    def pinned_names(self) -> set:
        with self._lock:
            return set(self._pinned_names)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def resident(self) -> Dict[str, int]:
        """Lock-free: the last published snapshot (one attribute read), so
        routing/stats never stall behind an in-progress checkpoint load."""
        return self._resident_snapshot

    def id_map(self) -> Dict[str, int]:
        """adapter_ids-compatible view: every KNOWN name maps to its device
        idx when resident, -1 when load-on-miss would have to run first.
        '' is the base model (device idx 0). Lock-free snapshot — submit's
        per-request membership check must not queue behind a load."""
        return self._id_map_snapshot

    def _publish_locked(self):
        """Rebuild the read snapshots; call at every point membership or a
        slot binding changed, while still holding the lock. Readers swap
        whole dicts — never a half-mutated view."""
        self._resident_snapshot = {n: e.slot for n, e in
                                   self._entries.items()
                                   if e.slot is not None}
        id_map = {"": 0}
        for n, e in self._entries.items():
            id_map[n] = e.slot if e.slot is not None else -1
        self._id_map_snapshot = id_map

    def describe(self, name: str) -> dict:
        with self._lock:
            ent = self._entries[name]
            return {"name": ent.name, "checkpoint": ent.checkpoint,
                    "resident": ent.slot is not None, "slot": ent.slot,
                    "pinned_by": ent.refs, "rank": ent.rank,
                    "loads": ent.loads, "loading": ent.loading}

    # ------------------------------------------------------------ occupancy
    def occupancy(self) -> dict:
        with self._lock:
            resident = [e for e in self._entries.values()
                        if e.slot is not None]
            return {
                "slots": self.store.pool_slots,
                "free": len(self._free_slots),
                "resident": len(resident),
                "pinned": sum(1 for e in resident if e.refs),
                "rank_max": self.store.rank_max,
                "targets": list(self.store.targets),
                "registered": len(self._entries),
                "hbm_bytes": self.store.nbytes(),
                **self.stats,
            }

    def host_tier_stats(self) -> Optional[dict]:
        """Host-RAM tier occupancy + the host_hits/orbax_loads load
        split, or None when the tier isn't configured (so consumers can
        gate their exposition on its presence)."""
        if self.host_tier is None:
            return None
        out = self.host_tier.stats()
        with self._lock:
            out["host_hits"] = self.host_hits
            out["orbax_loads"] = self.orbax_loads
        return out

    # ------------------------------------------------------- acquire/release
    def acquire(self, name: str, wait: bool = False,
                count_hit: bool = True) -> Optional[int]:
        """Resolve ``name`` to a device pool idx and pin it.

        NON-BLOCKING by default (the engine scheduler's contract): a miss
        reserves a slot — evicting the coldest UNPINNED resident when the
        pool is full — kicks the checkpoint read onto a loader thread, and
        returns None; the caller FIFO-waits and retries, succeeding once
        the load lands, while decode keeps ticking for everyone else.
        None is also the answer while every slot is pinned by in-flight
        work (KV-block-exhaustion semantics). ``wait=True`` blocks until
        the load resolves (scoring / admin warm-up paths, never the
        scheduler). ``count_hit=False`` suppresses the hit counter — a
        readmission RETRY of the same request (released its pin on
        KV-block exhaustion) is not a new lookup and must not inflate the
        hit rate. Raises KeyError for an unregistered name; a failed
        load's error (bad checkpoint, rank/target geometry) is re-raised
        by the next acquire of that name."""
        while True:
            with self._lock:
                ent = self._entries.get(name)
                if ent is None:
                    raise KeyError(
                        f"unknown adapter {name!r}; registered: "
                        f"{sorted(self._entries)}")
                if ent.error is not None:
                    err, ent.error = ent.error, None
                    raise err
                if ent.slot is not None:
                    ent.refs += 1
                    self._lru[name] = None
                    self._lru.move_to_end(name)
                    if ent.pending_first:
                        ent.pending_first = False  # the miss resolving
                    elif count_hit:
                        self.stats["hits"] += 1
                    return ent.slot
                if not ent.loading:
                    slot = self._take_slot_locked()
                    if slot is None:
                        return None  # pool exhausted: all pinned
                    self.stats["misses"] += 1
                    ent.loading = True
                    ent.event = threading.Event()
                    t = threading.Thread(target=self._load_worker,
                                         args=(ent, slot), daemon=True)
                    self._loader_threads = [
                        x for x in self._loader_threads if x.is_alive()]
                    self._loader_threads.append(t)
                    t.start()
                ev = ent.event
            if not wait:
                return None
            ev.wait()

    def release(self, name: str):
        with self._lock:
            ent = self._entries.get(name)
            if ent is not None and ent.refs > 0:
                ent.refs -= 1

    def close(self, timeout: float = 10.0):
        """Wait out in-flight async loads so no loader thread outlives the
        registry's owner (the engine joins its scheduler first, then calls
        this). Loads signal their waiters either way; ``timeout`` bounds a
        wedged checkpoint read from wedging shutdown."""
        with self._lock:
            threads = [t for t in self._loader_threads if t.is_alive()]
            self._loader_threads = []
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def preload(self, name: str):
        """Warm an adapter without pinning it (admin POST with load=true):
        blocking acquire + immediate release, so the next request is a
        residency hit. Raises the load's own error on a bad checkpoint."""
        idx = self.acquire(name, wait=True)
        if idx is None:
            raise RuntimeError(
                f"adapter pool exhausted ({self.store.pool_slots} slots, "
                "all pinned); cannot preload")
        self.release(name)

    # -------------------------------------------------------------- internal
    def _take_slot_locked(self) -> Optional[int]:
        if self._free_slots:
            return self._free_slots.pop(0)
        for victim_name in self._lru:  # front = coldest
            if victim_name in self._pinned_names:
                continue  # pin-tier tenants' adapters never evict
            victim = self._entries.get(victim_name)
            if victim is not None and victim.slot is not None \
                    and victim.refs == 0:
                slot = victim.slot
                self._evict_locked(victim)
                # _evict_locked returned the slot to the free list
                self._free_slots.remove(slot)
                return slot
        return None

    def _evict_locked(self, ent: _Entry):
        self.store.clear(ent.slot)
        self._free_slots.append(ent.slot)
        self._free_slots.sort()
        ent.slot = None
        self._lru.pop(ent.name, None)
        self.stats["evictions"] += 1
        self._publish_locked()

    def _load_worker(self, ent: _Entry, slot: int):
        """Loader thread: checkpoint read + validation run UNLOCKED (the
        multi-second part); only the device insert + bookkeeping take the
        lock. Failure frees the reserved slot and parks the error on the
        entry for the next acquire to raise."""
        t0 = time.perf_counter()
        try:
            cached = (self.host_tier.get(ent.name, ent.checkpoint)
                      if self.host_tier is not None else None)
            if cached is not None:
                # host-tier hit: evict→reload without the orbax read
                layers, scaling = cached
                from_host = True
            else:
                state = self._loader(ent.checkpoint)
                layers = (state.get("lora") or {}).get("layers")
                if not layers:
                    raise ValueError(
                        f"adapter {ent.name!r}: no lora tree in "
                        f"{ent.checkpoint}")
                from_host = False
                scaling = state.get("_scaling")
            rank = validate_adapter(layers, self.store.rank_max,
                                    self.store.targets, name=ent.name)
            if scaling is None:
                scaling = lora_scaling(32.0, rank)
            if self.host_tier is not None and not from_host:
                self.host_tier.put(ent.name, ent.checkpoint, layers,
                                   float(scaling))
        except Exception as e:  # noqa: BLE001 — parked for the acquirer
            self._load_failed(ent, slot, e)
            return
        with self._lock:
            try:
                # insert under the lock: concurrent loads to different
                # slots functionally rebuild the same pool buffers — an
                # unserialised read-modify-write would lose one insert
                self.store.insert(slot, layers, float(scaling),
                                  name=ent.name)
            except Exception as e:  # noqa: BLE001
                pass_e = e
            else:
                pass_e = None
                ent.slot = slot
                ent.rank = rank
                ent.loads += 1
                ent.loading = False
                ent.pending_first = True
                self._lru[ent.name] = None
                self._lru.move_to_end(ent.name)
                self.stats["loads"] += 1
                if from_host:
                    self.host_hits += 1
                else:
                    self.orbax_loads += 1
                ms = (time.perf_counter() - t0) * 1e3
                self.load_ms.append(ms)
                if len(self.load_ms) > 512:
                    del self.load_ms[:256]
                self._publish_locked()
                ev = ent.event
        if pass_e is not None:
            self._load_failed(ent, slot, pass_e)
            return
        ev.set()
        if self._load_observer is not None:
            self._load_observer(ms)
        if self._on_load_done is not None:
            self._on_load_done()

    def _load_failed(self, ent: _Entry, slot: int, err: BaseException):
        with self._lock:
            self._free_slots.append(slot)
            self._free_slots.sort()
            ent.loading = False
            ent.error = err
            ev = ent.event
        if ev is not None:
            ev.set()
        if self._on_load_done is not None:
            self._on_load_done()
