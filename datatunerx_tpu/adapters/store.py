"""AdapterStore: a fixed-geometry device pool of LoRA adapter weights.

The S-LoRA/Punica unlock (PAPER.md §0 end-state: one base model serving
hundreds of tenant adapters): adapter weights become **data** instead of
engine config. The store owns, per LoRA target, a stacked pool buffer

    a: [L, P + 1, d_in, rank_max]      b: [L, P + 1, rank_max, d_out]

(P usable pool slots + the reserved all-zero base slot 0) plus a scale
vector ``[P + 1]``. The layout is exactly the stacked-adapter tree
``models/llama.forward`` already consumes via ``lora_adapter_idx`` — each
batch row gathers its own slot inside the matmul — so a pool insert is a
functional ``.at[:, slot].set`` write and the decode program never changes
shape: loading/unloading an adapter at runtime causes ZERO recompiles
(the batched engine passes the pool as a program ARGUMENT, not a closure
constant, and jax keys executables on shapes only).

Adapters with rank < rank_max are zero-padded: zero columns of A and zero
rows of B contribute nothing to h·A·B, so padding is numerically invisible
(the parity tests assert token-exactness vs the unpadded stack). Adapters
with rank > rank_max or targets outside the pool's target set are rejected
with typed errors — the geometry is the program identity and cannot grow
at runtime.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.lora import DEFAULT_TARGETS, LORA_TARGETS, target_dims


class AdapterRankError(ValueError):
    """Adapter rank exceeds the pool's rank_max (a geometry violation —
    the pool would have to recompile to hold it)."""


class AdapterTargetError(ValueError):
    """Adapter trains a target projection the pool does not carry."""


def adapter_rank(layers: dict) -> int:
    """The (max, across targets) rank of a loaded adapter layer tree."""
    return max(np.asarray(leaf["a"]).shape[-1] for leaf in layers.values())


def validate_adapter(layers: dict, rank_max: int,
                     targets: Sequence[str], name: str = "") -> int:
    """Check a loaded adapter tree against the pool geometry; returns its
    rank. Raises AdapterRankError / AdapterTargetError with the numbers an
    operator needs to fix the mismatch."""
    label = f"adapter {name!r}" if name else "adapter"
    if not layers:
        raise ValueError(f"{label}: empty lora layer tree")
    extra = sorted(set(layers) - set(targets))
    if extra:
        raise AdapterTargetError(
            f"{label}: targets {extra} not in the pool's target set "
            f"{sorted(targets)}; restart the server with --adapter_targets "
            "covering them")
    rank = adapter_rank(layers)
    if rank > rank_max:
        raise AdapterRankError(
            f"{label}: rank {rank} exceeds the pool's rank_max {rank_max}; "
            "re-train at a lower rank or restart with a larger "
            "--adapter_rank_max")
    return rank


class AdapterStore:
    """Device pool buffers + slot bookkeeping. Mutations (insert/clear) are
    functional array updates that atomically republish ``self.tree`` — the
    scheduler thread reads that one attribute per tick, so a reader always
    sees a consistent (tree, scales) snapshot even while an admin thread
    loads an adapter."""

    def __init__(self, cfg: ModelConfig, pool_slots: int, rank_max: int,
                 targets: Sequence[str] = DEFAULT_TARGETS):
        if pool_slots < 1:
            raise ValueError(f"pool_slots must be >= 1, got {pool_slots}")
        if rank_max < 1:
            raise ValueError(f"rank_max must be >= 1, got {rank_max}")
        targets = tuple(sorted(set(targets)))
        bad = [t for t in targets if t not in LORA_TARGETS]
        if bad:
            raise ValueError(
                f"invalid lora targets {bad}; choices: {LORA_TARGETS}")
        self.cfg = cfg
        self.pool_slots = int(pool_slots)  # usable slots, device idx 1..P
        self.rank_max = int(rank_max)
        self.targets = targets
        L, E = cfg.num_layers, self.pool_slots + 1  # + base zero slot 0
        self._buffers: Dict[str, Dict[str, jnp.ndarray]] = {}
        for t in targets:
            d_in, d_out = target_dims(cfg, t)
            self._buffers[t] = {
                "a": jnp.zeros((L, E, d_in, rank_max), jnp.float32),
                "b": jnp.zeros((L, E, rank_max, d_out), jnp.float32),
            }
        self._scales = jnp.zeros((E,), jnp.float32)
        self.tree: Tuple[dict, jnp.ndarray] = self._republish()
        # Warm the clear() update programs now (clearing slot 1 is a no-op
        # on freshly zeroed buffers): the scalar .set(0.0) traces a
        # different program than insert's array .set, and without this the
        # FIRST eviction paid that compile mid-serving — caught by the
        # SAN003 compile_budget(0) window around runtime load/unload.
        self.clear(1)

    # ------------------------------------------------------------- geometry
    def geometry(self) -> tuple:
        """The pool's program-identity tuple (what the engine memo keys
        would need if the pool were a closure constant — it is not, so this
        is documentation + stats surface)."""
        return (self.pool_slots, self.rank_max, self.targets)

    def nbytes(self) -> int:
        """Device bytes the pool holds — the HBM the operator budgeted via
        adapterPool × adapterRankMax (README 'Multi-adapter serving')."""
        total = sum(int(buf["a"].nbytes) + int(buf["b"].nbytes)
                    for buf in self._buffers.values())
        return total + int(self._scales.nbytes)

    # ------------------------------------------------------------ mutations
    def _republish(self):
        layers = {t: dict(buf) for t, buf in self._buffers.items()}
        self.tree = ({"layers": layers}, self._scales)
        return self.tree

    def insert(self, slot: int, layers: dict, scaling: float,
               name: str = "") -> int:
        """Pad + write one adapter into pool ``slot`` (device idx 1..P).
        Validates geometry first; a rejected adapter leaves the pool
        untouched. Returns the adapter's rank."""
        self._check_slot(slot)
        rank = validate_adapter(layers, self.rank_max, self.targets,
                                name=name)
        L = self.cfg.num_layers
        for t in self.targets:
            buf = self._buffers[t]
            if t in layers:
                ar = np.asarray(layers[t]["a"], np.float32)  # [L, d_in, r]
                br = np.asarray(layers[t]["b"], np.float32)  # [L, r, d_out]
                if ar.shape[0] != L:
                    raise ValueError(
                        f"adapter {name!r}: {t} has {ar.shape[0]} layers, "
                        f"model has {L}")
                r = ar.shape[-1]
                a_row = np.zeros(
                    (L,) + buf["a"].shape[2:], np.float32)
                b_row = np.zeros(
                    (L,) + buf["b"].shape[2:], np.float32)
                a_row[:, :, :r] = ar
                b_row[:, :r, :] = br
            else:  # target absent from this adapter: zero delta
                a_row = np.zeros((L,) + buf["a"].shape[2:], np.float32)
                b_row = np.zeros((L,) + buf["b"].shape[2:], np.float32)
            buf["a"] = buf["a"].at[:, slot].set(jnp.asarray(a_row))
            buf["b"] = buf["b"].at[:, slot].set(jnp.asarray(b_row))
        self._scales = self._scales.at[slot].set(float(scaling))
        self._republish()
        return rank

    def clear(self, slot: int):
        """Zero a slot (eviction hygiene: a stale adapter must never leak
        into a request that lands on a recycled slot before its insert)."""
        self._check_slot(slot)
        for buf in self._buffers.values():
            buf["a"] = buf["a"].at[:, slot].set(0.0)
            buf["b"] = buf["b"].at[:, slot].set(0.0)
        self._scales = self._scales.at[slot].set(0.0)
        self._republish()

    def _check_slot(self, slot: int):
        if not 1 <= slot <= self.pool_slots:
            raise ValueError(
                f"pool slot {slot} out of range 1..{self.pool_slots} "
                "(slot 0 is the reserved base adapter)")


def hbm_bytes(cfg: ModelConfig, pool_slots: int, rank_max: int,
              targets: Sequence[str] = DEFAULT_TARGETS) -> int:
    """Pool HBM for a geometry WITHOUT building it — the operator-facing
    sizing helper the README table uses."""
    L, E = cfg.num_layers, pool_slots + 1
    total = E * 4  # scales float32
    for t in sorted(set(targets)):
        d_in, d_out = target_dims(cfg, t)
        total += 4 * L * E * rank_max * (d_in + d_out)
    return total
