"""HostAdapterTier: bounded host-RAM LRU of adapter weights.

The dynamic adapter pool (PR 10) holds N adapters in HBM; everything else
is an orbax checkpoint read away — hundreds of ms to seconds per reload,
paid again every time the LRU churns. This tier keeps EVICTED adapters'
host arrays (the exact layers/scaling the registry loader produced) in a
byte-budgeted host LRU, so evict→reload becomes host→device insert with
zero orbax reads. The registry counts ``host_hits`` separately from
``orbax_loads`` so the split is observable and the zero-orbax-reload
contract is testable.

Entries are keyed (adapter name, checkpoint path): re-registering a name
at a different checkpoint can never serve the stale weights.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple


def _entry_bytes(layers) -> int:
    """Host-side footprint of one adapter's layer tree — the registry
    loader's ``{target: {"a": arr, "b": arr}}`` shape, walked generically
    so list/tuple-shaped stacks size correctly too."""
    if isinstance(layers, dict):
        return sum(_entry_bytes(v) for v in layers.values())
    if isinstance(layers, (list, tuple)):
        return sum(_entry_bytes(v) for v in layers)
    return int(getattr(layers, "nbytes", 0) or 0)


class HostAdapterTier:
    """Thread-safe LRU of (layers, scaling) keyed (name, checkpoint),
    bounded by ``max_bytes``. Oversized singles are refused rather than
    thrashing the whole tier out."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._d: "OrderedDict[Tuple[str, str], dict]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0

    def get(self, name: str, checkpoint: str) -> Optional[tuple]:
        """→ (layers, scaling) and refresh recency, or None."""
        key = (name, checkpoint)
        with self._lock:
            ent = self._d.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._d.move_to_end(key)
            self.hits += 1
            return ent["layers"], ent["scaling"]

    def put(self, name: str, checkpoint: str, layers, scaling) -> bool:
        """Insert (refreshing an existing key), evicting coldest-first to
        fit. Returns False when the entry alone exceeds the budget."""
        nbytes = _entry_bytes(layers)
        if nbytes <= 0 or nbytes > self.max_bytes:
            return False
        key = (name, checkpoint)
        with self._lock:
            old = self._d.pop(key, None)
            if old is not None:
                self._bytes -= old["bytes"]
            while self._d and self._bytes + nbytes > self.max_bytes:
                _, cold = self._d.popitem(last=False)
                self._bytes -= cold["bytes"]
                self.evictions += 1
            self._d[key] = {"layers": layers, "scaling": scaling,
                            "bytes": nbytes}
            self._bytes += nbytes
            self.puts += 1
            return True

    def drop(self, name: str) -> int:
        """Forget every checkpoint cached under ``name`` (the registry's
        unregister path) — a deleted adapter must not resurrect."""
        with self._lock:
            doomed = [k for k in self._d if k[0] == name]
            for k in doomed:
                self._bytes -= self._d.pop(k)["bytes"]
            return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._d), "bytes": self._bytes,
                    "max_bytes": self.max_bytes, "hits": self.hits,
                    "misses": self.misses, "puts": self.puts,
                    "evictions": self.evictions}
