"""Multi-tenant QoS plane: tenants as a first-class scheduling dimension.

The serving fleet pools everything — one adapter cache (PR 10), one KV
block pool (PR 15), one disaggregated fleet plane (PR 16) — but until this
package every caller was one anonymous tenant. ``TenantDirectory`` maps a
tenant to a tier (pinned / standard / bulk), its adapter set, a pool-share
weight and a KV-block quota; ``HostAdapterTier`` is the bounded host-RAM
LRU that turns evict→reload from an orbax read into a host→device copy.

Gating contract (the PR 15/16 pattern): with no tenant config, nothing
here is constructed and the gateway, engine and both /metrics expositions
stay byte-identical to a tenancy-less build.
"""

from datatunerx_tpu.tenancy.directory import (
    TIERS,
    TenantDirectory,
    TenantSpec,
    load_tenants,
    tenant_entry_from_crd,
    validate_tenant_entry,
)
from datatunerx_tpu.tenancy.host_tier import HostAdapterTier

__all__ = [
    "TIERS",
    "TenantDirectory",
    "TenantSpec",
    "HostAdapterTier",
    "load_tenants",
    "tenant_entry_from_crd",
    "validate_tenant_entry",
]
