"""TenantDirectory: tenant → tier / adapter set / pool share / KV quota.

One config object feeds every plane: the gateway resolves a tenant per
request (``X-DTX-Tenant`` header first, adapter/model name second) and
prices admission against the tenant's share and block quota; the engine
tags requests so overcommit preemption is tier-aware; the adapter registry
pins the adapters of pinned-tier tenants against LRU eviction.

Config is a JSON object — a file path, an inline JSON string, or an
already-parsed dict — shaped::

    {"acme":  {"tier": "pinned",  "adapters": ["acme-chat"],
               "share": 4, "kv_block_quota": 0, "ttft_p95_ms": 250},
     "batch": {"tier": "bulk", "adapters": ["batch-sum"], "share": 1,
               "kv_block_quota": 16}}

Tier semantics:

  pinned   — adapters immune to pool LRU eviction; decode sessions are
             never preempted on behalf of a bulk tenant.
  standard — the default; rides the pool LRU and youngest-first
             preemption exactly like an un-tenanted request.
  bulk     — first in line for preemption and eviction; throughput
             traffic that paid for capacity, not latency.

``share`` is a smooth-WRR-style weight: when the admission token budget
is contended, tenant *i* may hold ``share_i / Σ shares`` of it.
``kv_block_quota`` caps the tenant's in-flight admission-priced KV blocks
(0 = uncapped). ``ttft_p95_ms`` is an optional per-tenant objective the
gateway's /autoscale burn branch reads.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

TIERS = ("pinned", "standard", "bulk")

# preemption priority per tier: LOWER ranks are preempted first. Bulk
# gives way to everyone, pinned to no one (on behalf of a bulk requester).
TIER_RANK = {"bulk": 0, "standard": 1, "pinned": 2}


def validate_tenant_entry(name: str, entry: dict) -> None:
    """Raise ValueError naming the field on any malformed tenant entry —
    the ONE validator shared by the directory loader and the operator
    admission webhook, so `kubectl apply` and `--tenants_config` reject
    identically."""
    if not name or not isinstance(name, str):
        raise ValueError("tenant name must be a non-empty string")
    if not isinstance(entry, dict):
        raise ValueError(f"tenant {name!r}: entry must be an object")
    tier = entry.get("tier", "standard")
    if tier not in TIERS:
        raise ValueError(
            f"tenant {name!r}: tier must be one of {'/'.join(TIERS)}, "
            f"got {tier!r}")
    adapters = entry.get("adapters", [])
    if not isinstance(adapters, list) or \
            not all(isinstance(a, str) and a for a in adapters):
        raise ValueError(
            f"tenant {name!r}: adapters must be a list of adapter names")
    share = entry.get("share", 1)
    try:
        share_f = float(share)
    except (TypeError, ValueError):
        raise ValueError(f"tenant {name!r}: share must be a number")
    if share_f <= 0:
        raise ValueError(f"tenant {name!r}: share must be > 0")
    for key in ("kv_block_quota", "ttft_p95_ms"):
        v = entry.get(key, 0)
        try:
            v_f = float(v)
        except (TypeError, ValueError):
            raise ValueError(f"tenant {name!r}: {key} must be a number")
        if v_f < 0:
            raise ValueError(f"tenant {name!r}: {key} must be >= 0")


_CRD_KEYS = {"kvBlockQuota": "kv_block_quota", "ttftP95Ms": "ttft_p95_ms"}


def tenant_entry_from_crd(entry: dict) -> dict:
    """Map a serveConfig.tenants entry's camelCase keys onto the
    directory's snake_case schema — the webhook and generate_serving_spec
    share this so `kubectl apply` and `--tenants_config` see one shape."""
    return {_CRD_KEYS.get(k, k): v for k, v in (entry or {}).items()}


class TenantSpec:
    """One tenant's policy row (immutable value object)."""

    __slots__ = ("name", "tier", "adapters", "share", "kv_block_quota",
                 "ttft_p95_ms")

    def __init__(self, name: str, tier: str = "standard",
                 adapters: Optional[List[str]] = None,
                 share: float = 1.0, kv_block_quota: int = 0,
                 ttft_p95_ms: float = 0.0):
        validate_tenant_entry(name, {
            "tier": tier, "adapters": list(adapters or []),
            "share": share, "kv_block_quota": kv_block_quota,
            "ttft_p95_ms": ttft_p95_ms})
        self.name = name
        self.tier = tier
        self.adapters = tuple(adapters or [])
        self.share = float(share)
        self.kv_block_quota = int(kv_block_quota)
        self.ttft_p95_ms = float(ttft_p95_ms)

    @classmethod
    def from_dict(cls, name: str, entry: dict) -> "TenantSpec":
        validate_tenant_entry(name, entry)
        return cls(name,
                   tier=entry.get("tier", "standard"),
                   adapters=list(entry.get("adapters") or []),
                   share=float(entry.get("share", 1)),
                   kv_block_quota=int(entry.get("kv_block_quota", 0) or 0),
                   ttft_p95_ms=float(entry.get("ttft_p95_ms", 0) or 0))

    def to_dict(self) -> dict:
        return {"tier": self.tier, "adapters": list(self.adapters),
                "share": self.share, "kv_block_quota": self.kv_block_quota,
                "ttft_p95_ms": self.ttft_p95_ms}


class TenantDirectory:
    """Thread-safe tenant registry with per-request resolution.

    Mutable at runtime (``POST /admin/tenants`` upserts a row), so every
    read snapshots under the lock; consumers that cache derived views
    (the registry's pinned-adapter set) re-pull after an upsert via the
    directory's generation counter.
    """

    def __init__(self, tenants: Optional[Dict[str, dict]] = None):
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantSpec] = {}
        self._generation = 0
        for name, entry in (tenants or {}).items():
            spec = (entry if isinstance(entry, TenantSpec)
                    else TenantSpec.from_dict(name, entry))
            self._tenants[name] = spec
        self._reindex_locked()

    # ------------------------------------------------------------- views
    def _reindex_locked(self):
        self._by_adapter = {}
        for spec in self._tenants.values():
            for a in spec.adapters:
                # first-writer wins on a contested adapter name — config
                # order is dict order, which JSON preserves
                self._by_adapter.setdefault(a, spec)
        self._generation += 1

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def get(self, name: str) -> Optional[TenantSpec]:
        with self._lock:
            return self._tenants.get(name)

    def resolve(self, tenant: str = "",
                adapter: str = "") -> Optional[TenantSpec]:
        """The tenant a request belongs to: an explicit tenant name (the
        ``X-DTX-Tenant`` header) wins; else the adapter/model name maps
        through the tenants' adapter sets; else None (anonymous — every
        plane treats None exactly like the pre-tenancy build)."""
        with self._lock:
            if tenant and tenant in self._tenants:
                return self._tenants[tenant]
            if adapter:
                return self._by_adapter.get(adapter)
            return None

    def tier_of_adapter(self, adapter: str) -> str:
        spec = self.resolve(adapter=adapter)
        return spec.tier if spec is not None else "standard"

    def pinned_adapters(self) -> set:
        """Adapters of pinned-tier tenants — the registry's LRU skips
        them as eviction victims."""
        with self._lock:
            return {a for s in self._tenants.values()
                    if s.tier == "pinned" for a in s.adapters}

    def shares(self) -> Dict[str, float]:
        with self._lock:
            return {n: s.share for n, s in self._tenants.items()}

    def to_dict(self) -> Dict[str, dict]:
        with self._lock:
            return {n: s.to_dict() for n, s in sorted(self._tenants.items())}

    # ----------------------------------------------------------- updates
    def upsert(self, name: str, entry: dict) -> TenantSpec:
        spec = TenantSpec.from_dict(name, entry)
        with self._lock:
            self._tenants[name] = spec
            self._reindex_locked()
        return spec

    def remove(self, name: str) -> bool:
        with self._lock:
            present = self._tenants.pop(name, None) is not None
            if present:
                self._reindex_locked()
            return present


def load_tenants(config: object) -> Optional[TenantDirectory]:
    """Build a directory from a file path, inline JSON text, or dict —
    the one loader behind ``--tenants_config`` on both servers and the
    serveConfig pass-through. Falsy input → None (tenancy plane off)."""
    if not config:
        return None
    if isinstance(config, TenantDirectory):
        return config
    obj = config
    if isinstance(config, str):
        text = config.strip()
        if not text.startswith("{"):
            with open(config, encoding="utf-8") as f:
                text = f.read()
        try:
            obj = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"tenants config is not valid JSON: {e}")
    if not isinstance(obj, dict):
        raise ValueError("tenants config must be a JSON object "
                         "{tenant: {tier, adapters, share, ...}}")
    # accept both the bare map and a {"tenants": {...}} envelope (the CRD
    # serveConfig uses the bare map; the envelope reads naturally in a
    # standalone file)
    if "tenants" in obj and isinstance(obj["tenants"], dict) \
            and all(isinstance(v, dict) for v in obj["tenants"].values()):
        obj = obj["tenants"]
    if not obj:
        return None
    return TenantDirectory(obj)
