"""Per-device HBM footprint accounting (VERDICT r3 next-round #4).

The reference has nothing like this — an oversized job simply OOMs on the
worker (SURVEY §7.4#1 names capacity the hard part of the TPU port). Here
the byte math is done up front:

- ``estimate_footprint`` sums params, LoRA adapters, optimizer state,
  gradients, and remat-policy activation peaks into bytes/device for a
  given model config, train config, batch geometry, and mesh shape.
- Param/optimizer/gradient trees are counted EXACTLY via ``jax.eval_shape``
  over the same ``init_params`` / ``quantize_model_params`` /
  ``optimizer.init`` calls the trainer makes — no drift between the
  estimate and the real program — then divided per-leaf by the shard
  factors of `parallel/sharding.py`'s partition specs.
- Activations are an analytic model of the remat policy (documented per
  term below) with a safety margin; they are the only approximate term.
- ``check_fits`` turns the estimate into an admission verdict for the
  operator (finetune_controller rejects oversized jobs instead of letting
  them OOM on-slice).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.parallel.sharding import _spec_for

# Usable HBM per chip by generation. Totals are 16/32/95 GB; XLA reserves a
# slice for its own workspace (scratch for fusions, collectives, infeed), so
# admission budgets against ~94% of the total.
HBM_BYTES = {
    "v4": 32e9,
    "v5e": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
}
XLA_RESERVE_FRACTION = 0.06
# Analytic activation model error margin (the exact terms depend on XLA
# fusion decisions; ±10% covers the observed spread at debug/1B scale).
ACTIVATION_MARGIN = 1.10


@dataclasses.dataclass(frozen=True)
class Footprint:
    """Bytes per device, by component."""

    params: int
    lora: int
    opt_state: int
    grads: int
    activations: int
    logits: int
    fsdp_gather: int = 0  # XLA's whole-stack weight gathers (fsdp>1 only)

    @property
    def total(self) -> int:
        return (self.params + self.lora + self.opt_state + self.grads
                + self.activations + self.logits + self.fsdp_gather)

    def gb(self) -> Dict[str, float]:
        d = {f.name: round(getattr(self, f.name) / 1e9, 3)
             for f in dataclasses.fields(self)}
        d["total"] = round(self.total / 1e9, 3)
        return d


def _shard_divisor(path, x, mesh_shape: Dict[str, int]) -> int:
    """Product of mesh-axis sizes the sharding rules split this leaf over."""
    spec = _spec_for(tuple(getattr(k, "key", k) for k in path), x)
    div = 1
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            div *= mesh_shape.get(ax, 1)
    return div


def _tree_bytes(tree, mesh_shape: Dict[str, int],
                dtype_override=None) -> int:
    """Sum of per-device leaf bytes for a ShapeDtypeStruct (or array) tree."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        size = math.prod(leaf.shape) if leaf.shape else 1
        itemsize = (jnp.dtype(dtype_override).itemsize if dtype_override
                    else jnp.dtype(leaf.dtype).itemsize)
        total += math.ceil(size / _shard_divisor(path, leaf, mesh_shape)
                           ) * itemsize
    return total


def estimate_footprint(
    model_cfg: ModelConfig,
    train_cfg,
    *,
    batch: int,
    seq: int,
    mesh_shape: Optional[Dict[str, int]] = None,
    compute_dtype=jnp.bfloat16,
) -> Footprint:
    """Bytes/device for one train step of ``Trainer`` at this geometry.

    ``mesh_shape`` maps axis name → size ({'dp':1,'fsdp':8,'tp':1,'sp':1});
    missing axes default to 1 (single chip = all 1s).
    """
    from datatunerx_tpu.models import init_params
    from datatunerx_tpu.models.lora import init_lora_params
    from datatunerx_tpu.training.optimizer import make_optimizer

    mesh_shape = dict(mesh_shape or {})
    cdt = jnp.dtype(compute_dtype).itemsize
    key = jax.random.PRNGKey(0)

    # ---- params (exact): the same init(+quantize) call the trainer makes
    def build_params(k):
        p = init_params(model_cfg, k, dtype=compute_dtype)
        if model_cfg.quantization:
            from datatunerx_tpu.ops.quant import quantize_model_params

            p = quantize_model_params(p, model_cfg.quantization)
        return p

    params_shape = jax.eval_shape(build_params, key)
    params_bytes = _tree_bytes(params_shape, mesh_shape)

    # ---- trainable tree (exact)
    lora_bytes = 0
    if train_cfg.finetuning_type == "lora":
        lora_shape = jax.eval_shape(
            lambda k: init_lora_params(
                model_cfg, k, rank=train_cfg.lora_rank,
                targets=tuple(train_cfg.lora_targets)), key)
        lora_bytes = _tree_bytes(lora_shape, mesh_shape)
        trainable_shape = lora_shape
    elif train_cfg.finetuning_type == "none":
        trainable_shape = None
    else:  # full / freeze: the base params are the trainable tree
        trainable_shape = params_shape

    # ---- optimizer state (exact): adamw = 2 fp32 moments per trainable
    opt_bytes = 0
    if trainable_shape is not None:
        optimizer = make_optimizer(
            train_cfg.optimizer, train_cfg.learning_rate,
            weight_decay=train_cfg.weight_decay,
            max_grad_norm=train_cfg.max_grad_norm)
        opt_shape = jax.eval_shape(optimizer.init, trainable_shape)
        opt_bytes = _tree_bytes(opt_shape, mesh_shape)

    # ---- gradients: one trainable-shaped tree, fp32 accumulation worst-case
    grad_bytes = 0
    if trainable_shape is not None:
        grad_bytes = _tree_bytes(trainable_shape, mesh_shape,
                                 dtype_override=jnp.float32)

    # ---- activations (analytic): local batch/seq after sharding.
    # batch shards over (dp, fsdp); seq over sp; grad_accum microbatches the
    # LOCAL batch (scan carries one microbatch of activations at a time).
    data_shards = mesh_shape.get("dp", 1) * mesh_shape.get("fsdp", 1)
    tp = mesh_shape.get("tp", 1)
    b = math.ceil(batch / data_shards)
    b = math.ceil(b / max(1, getattr(train_cfg, "grad_accum", 1)))
    t = math.ceil(seq / mesh_shape.get("sp", 1))
    H = model_cfg.hidden_size
    L = model_cfg.num_layers
    I = model_cfg.intermediate_size  # noqa: E741
    V = model_cfg.vocab_size

    # ---- fsdp weight-gather live set: with parameters sharded over fsdp,
    # XLA all-gathers weights to compute. For the scan-stacked layout it
    # chooses to gather some stacked kernels WHOLE (outside the loop), not
    # per-layer: compiler buffer assignment for Mistral-7B full-param
    # fsdp=16 shows ~9 GB of temps ≈ the two largest stacked kernels
    # gathered in full (AOT_CERTIFY.json step/train_mistral7b_full_fsdp16,
    # r5). Model that observed behavior: the two largest fsdp-sharded
    # stacked leaves, un-sharded. Zero when fsdp == 1 (nothing to gather).
    fsdp = mesh_shape.get("fsdp", 1)
    gather_bytes = 0
    if fsdp > 1:
        stacked = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(params_shape)[0]:
            if _shard_divisor(path, leaf, {"fsdp": fsdp}) > 1:
                size = math.prod(leaf.shape) if leaf.shape else 1
                stacked.append(size * jnp.dtype(leaf.dtype).itemsize)
        gather_bytes = sum(sorted(stacked, reverse=True)[:2])

    if model_cfg.remat in ("full", "dots"):
        # stored across the whole fwd: the per-layer boundary residual
        # stream (fwd copy + its gradient in the bwd sweep)
        boundaries = 2 * L * b * t * H * cdt
        if model_cfg.remat == "dots":
            # checkpoint_dots also saves every matmul output inside the
            # layer: qkv+o (≈2H eff. with GQA ≤ 2H + small), gate/up/down
            # (2I + H) — per layer, tp-sharded
            boundaries += L * b * t * (3 * H + 2 * I) // tp * cdt
        # recompute live set: ONE layer's internals during its bwd
        if model_cfg.attention_impl == "xla":
            attn = 2 * b * model_cfg.num_heads * t * t * 4 // tp  # fp32 scores
        else:  # flash/ring never materialize [T, T]
            attn = 4 * b * t * (model_cfg.q_dim + 2 * model_cfg.kv_dim
                                ) // tp * cdt
        mlp = 6 * b * t * I // tp * cdt  # gate/up/act fwd + bwd mirrors
        act_bytes = boundaries + max(attn, mlp)
    else:  # remat none: every layer's internals stay live for the bwd
        if model_cfg.attention_impl == "xla":
            per_layer = (2 * b * model_cfg.num_heads * t * t * 4 // tp
                         + 4 * b * t * H * cdt)
        else:
            per_layer = (4 * b * t * (model_cfg.q_dim + 2 * model_cfg.kv_dim)
                         // tp * cdt + 4 * b * t * H * cdt)
        per_layer += 3 * b * t * I // tp * cdt
        act_bytes = L * per_layer
    act_bytes = int(act_bytes * ACTIVATION_MARGIN)

    # ---- logits: [b, t, V] in compute dtype + the fp32 cast the loss makes
    # (training/loss.py:23) + its gradient; V shards over tp (lm_head spec)
    logits_bytes = b * t * math.ceil(V / tp) * (cdt + 4 + 4)

    return Footprint(
        params=params_bytes, lora=lora_bytes, opt_state=opt_bytes,
        grads=grad_bytes, activations=act_bytes, logits=logits_bytes,
        fsdp_gather=gather_bytes,
    )


def hbm_budget(generation: str = "v5e") -> int:
    """Admission budget: usable HBM/chip after the XLA workspace reserve."""
    if generation not in HBM_BYTES:
        raise KeyError(f"unknown TPU generation {generation!r}; "
                       f"have {sorted(HBM_BYTES)}")
    return int(HBM_BYTES[generation] * (1 - XLA_RESERVE_FRACTION))


def check_fits(
    model_cfg: ModelConfig,
    train_cfg,
    *,
    batch: int,
    seq: int,
    mesh_shape: Optional[Dict[str, int]] = None,
    generation: str = "v5e",
) -> tuple:
    """→ (fits: bool, footprint: Footprint, budget_bytes: int)."""
    fp = estimate_footprint(model_cfg, train_cfg, batch=batch, seq=seq,
                            mesh_shape=mesh_shape)
    budget = hbm_budget(generation)
    return fp.total <= budget, fp, budget
