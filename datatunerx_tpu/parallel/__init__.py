from datatunerx_tpu.parallel.mesh import MESH_AXES, make_mesh, mesh_shape_for
from datatunerx_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
    shard_tree,
    tree_shardings,
)

__all__ = [
    "MESH_AXES",
    "make_mesh",
    "mesh_shape_for",
    "batch_pspec",
    "param_pspecs",
    "shard_tree",
    "tree_shardings",
]
