"""Device mesh construction: the TPU-native replacement for Ray worker groups.

The reference expresses cluster shape as KubeRay head+workers with one GPU each
and scales via torch-DDP allreduce (SURVEY.md §2.4). Here the unit of scale is a
`jax.sharding.Mesh` over all addressable chips with named axes:

  dp   — pure data parallelism (params replicated)
  fsdp — data parallelism with param/optimizer sharding (ZeRO-3-style, GSPMD)
  tp   — tensor parallelism (megatron-style column/row splits)
  sp   — sequence/context parallelism for ring attention (long context)

GSPMD inserts the collectives (all-reduce / all-gather / reduce-scatter) over
ICI; nothing here talks NCCL/MPI (SURVEY.md §5.8).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

MESH_AXES = ("dp", "fsdp", "tp", "sp")


def mesh_shape_for(
    n_devices: int,
    *,
    dp: Optional[int] = None,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
) -> tuple[int, int, int, int]:
    """Resolve a (dp, fsdp, tp, sp) shape filling the unspecified data axis.

    Exactly one of dp/fsdp may be None; it absorbs the remaining devices.
    """
    fixed = tp * sp
    if dp is None and fsdp is None:
        dp, fsdp = n_devices // fixed, 1
    elif dp is None:
        dp = n_devices // (fsdp * fixed)
    elif fsdp is None:
        fsdp = n_devices // (dp * fixed)
    shape = (dp, fsdp, tp, sp)
    if math.prod(shape) != n_devices:
        raise ValueError(
            f"mesh shape {dict(zip(MESH_AXES, shape))} != {n_devices} devices"
        )
    return shape


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    *,
    devices=None,
    dp: Optional[int] = None,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    dcn_dp: int = 1,
) -> Mesh:
    """Build the 4-axis mesh. Axis order puts dp/fsdp outermost so data-parallel
    replicas land on distinct ICI neighborhoods and tp rides the innermost
    (fastest) links.

    Multi-slice: ``dcn_dp`` > 1 splits the dp axis hierarchically — its MAJOR
    dimension crosses slices over DCN, everything else (fsdp/tp/sp and the
    minor dp) stays inside a slice on ICI. The axis names don't change, so
    shardings/collectives are untouched; only the device ORDER encodes slice
    locality (gradient all-reduce then decomposes into intra-slice reduce +
    one cross-slice exchange, the standard multislice recipe). On hardware
    with slice indices the hybrid mesh builder assigns devices; elsewhere
    (CPU testing) contiguous chunks of the device list emulate slices.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    if shape is None:
        shape = mesh_shape_for(len(devices), dp=dp, fsdp=fsdp, tp=tp, sp=sp)
    shape = tuple(shape)
    if len(shape) != 4:
        raise ValueError(f"expected 4-axis shape {MESH_AXES}, got {shape}")
    # Auto axis types = classic GSPMD: the compiler propagates shardings from
    # NamedSharding annotations (jax>=0.9 defaults to Explicit mode otherwise).
    # jax < 0.5 has no AxisType — every axis is implicitly Auto there, so the
    # kwarg is simply omitted and the same programs compile unchanged.
    if hasattr(jax.sharding, "AxisType"):
        axis_kw = {"axis_types": (jax.sharding.AxisType.Auto,) * 4}
    else:
        axis_kw = {}
    if dcn_dp <= 1:
        return jax.make_mesh(shape, MESH_AXES, devices=devices, **axis_kw)

    if shape[0] % dcn_dp != 0:
        raise ValueError(
            f"dp={shape[0]} must be divisible by dcn_dp={dcn_dp} "
            "(cross-slice parallelism rides the dp axis)"
        )
    import numpy as np

    per_slice = (shape[0] // dcn_dp, shape[1], shape[2], shape[3])
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            per_slice, (dcn_dp, 1, 1, 1), devices=devices
        )
    else:
        # no slice topology (CPU / single-slice): dp-major contiguity of the
        # flat device list already IS slice-major order, so a plain reshape
        # emulates slices — the same program shape compiles and runs
        arr = np.array(devices).reshape(shape)
    return Mesh(arr, MESH_AXES, **axis_kw)
