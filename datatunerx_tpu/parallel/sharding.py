"""GSPMD sharding rules for the stacked-param tree.

Replaces DeepSpeed ZeRO config (reference cmd/tuning/ds_config.json — shipped at
stage 0, i.e. no sharding at all) with first-class partition specs:

- `fsdp` shards the contraction dim of every kernel (ZeRO-3-equivalent: params,
  grads and optimizer state all sharded; XLA all-gathers just-in-time).
- `tp` shards the output dim of column-parallel kernels (q/k/v/gate/up) and the
  input dim of row-parallel kernels (o/down) — megatron layout, so each block
  needs a single psum pair inserted by GSPMD.
- Activations shard batch over (dp, fsdp) and model dim over tp.

Rules are path-based over the HF-style leaf names, so they apply equally to the
base params, LoRA adapters, gradients, and optimizer-state mirrors.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (leaf-name match, array rank) → spec builder. Stacked layer axis (leading, rank-3
# kernels) is never sharded: every device owns every layer slice it needs.
_COLUMN = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"}
_ROW = {"o_proj", "down_proj"}


_MODULES = _COLUMN | _ROW | {"lm_head", "embed_tokens"}


def _spec_for(path: tuple[str, ...], x: Any) -> P:
    names = [p for p in path if isinstance(p, str)]
    leaf = names[-1] if names else ""
    # the owning module may sit deeper than names[-2] (e.g.
    # layers/<proj>/quant/<leaf>) — search the path for a known module name
    module = next((n for n in names if n in _MODULES), "")
    rank = getattr(x, "ndim", len(getattr(x, "shape", ())))

    if leaf == "embedding":  # [V, D]
        return P("tp", "fsdp")
    if module == "lm_head":  # [D, V]
        return P("fsdp", "tp")
    if leaf == "a" and rank == 3:  # LoRA A [L, in, r]
        return P(None, "fsdp" if module in _COLUMN else "tp", None)
    if leaf == "b" and rank == 3:  # LoRA B [L, r, out]
        return P(None, None, "tp" if module in _COLUMN else "fsdp")
    if leaf == "kernel" and rank == 3:  # [L, in, out]
        if module in _ROW:
            return P(None, "tp", "fsdp")
        return P(None, "fsdp", "tp")
    if leaf == "q" and rank == 3:  # int8 kernel [L, in, out] (ops/quant.py)
        if module in _ROW:
            return P(None, "tp", "fsdp")
        return P(None, "fsdp", "tp")
    if leaf == "bias" and rank == 2:  # [L, out]
        return P(None, "tp" if module in _COLUMN else "fsdp")
    if leaf == "scale" and rank == 2 and (module in _COLUMN or module in _ROW):
        # int8 per-channel scales [L, out]
        return P(None, "tp" if module in _COLUMN else "fsdp")
    if leaf in ("packed", "scale_q") and rank >= 2:
        # nf4 blocks are output-channel-contiguous: shard the block axis
        return P(None, "fsdp", *([None] * (rank - 2)))
    if leaf == "scale":  # norms — tiny, replicate
        return P()
    # optimizer-state scalars (counts) and anything unrecognized: replicate
    if rank == 0:
        return P()
    return P()


def param_pspecs(tree) -> Any:
    """Pytree of PartitionSpec matching `tree` (params / lora / grads / opt state)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: _spec_for(tuple(getattr(k, "key", k) for k in path), x), tree
    )


def tree_shardings(tree, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_pspecs(tree),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree, mesh: Mesh) -> Any:
    """device_put `tree` onto the mesh according to the param rules."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, tree_shardings(tree, mesh)
    )


def batch_pspec(rank: int = 2, accum: bool = False) -> P:
    """Token batches [B, T, ...]: batch over (dp, fsdp), sequence over sp.

    With gradient accumulation the leading axis is the scan axis [A, mb, T] —
    it must stay unsharded (every device steps through all A microbatches) and
    the *microbatch* axis carries the data parallelism.
    """
    if accum:
        return P(None, ("dp", "fsdp"), "sp", *([None] * (rank - 3)))
    return P(("dp", "fsdp"), "sp", *([None] * (rank - 2)))


def batch_shardings(batch, mesh: Mesh, accum: bool = False) -> Any:
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, batch_pspec(x.ndim, accum=accum)), batch
    )


def compat_shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions: the top-level API (with
    ``check_vma``) exists from jax 0.6; older jax ships it as
    ``jax.experimental.shard_map.shard_map`` with the ``check_rep`` spelling
    of the same knob."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)


def place_batch(batch: dict, mesh: Optional[Mesh], accum: bool = False) -> dict:
    """Place one host-local batch dict onto the mesh.

    Single source of truth for batch placement — the Trainer's inline path and
    the DevicePrefetcher (data/prefetch.py) both call this, so pipelined and
    synchronous feeding are byte-identical. Batches handed in are HOST-LOCAL
    slices: single-process (host slice == global batch) uses a plain
    device_put; multi-host assembles the global array from per-process slices —
    device_put there would misread the local slice as the global array (half
    the data silently dropped)."""
    flat = {k: v for k, v in batch.items() if v is not None}
    if mesh is None:
        return flat
    sh = batch_shardings(flat, mesh, accum=accum)
    if jax.process_count() > 1:
        import numpy as np

        return {
            # v is the host-local numpy slice from the input pipeline (never
            # a device array): asarray is the no-copy coercion
            # make_array_from_process_local_data requires, not a device sync
            k: jax.make_array_from_process_local_data(sh[k], np.asarray(v))  # dtxlint: disable=DTX001 -- host numpy, no sync
            for k, v in flat.items()
        }
    return {k: jax.device_put(v, sh[k]) for k, v in flat.items()}
