"""Multi-host bootstrap: `jax.distributed.initialize` from pod environment.

Replaces the reference's Ray control plane (ray.init + Ray Train worker
placement, reference cmd/tuning/train.py:310,353-377). In the TPU-native design
(SURVEY.md §5.8) a JobSet/StatefulSet of TPU-host pods runs ONE identical
program; pod 0 is the coordinator and GSPMD handles all cross-host collectives,
so "distributed setup" reduces to this single call.

Env contract (set by the operator's job generator, operator/generate.py):
  DTX_COORDINATOR_ADDRESS  host:port of pod 0 (default port 8476)
  DTX_NUM_PROCESSES        total host count
  DTX_PROCESS_ID           this host's index
Falls back to JAX's own autodetection (GKE JobSet / TPU metadata) when unset.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def maybe_initialize_distributed(num_workers: int = 1) -> dict:
    """Initialize jax.distributed when running multi-host; no-op otherwise.

    Returns a summary dict {initialized, process_id, num_processes}.
    """
    if num_workers <= 1 and "DTX_COORDINATOR_ADDRESS" not in os.environ:
        return {"initialized": False, "process_id": 0, "num_processes": 1}

    coord: Optional[str] = os.environ.get("DTX_COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("DTX_NUM_PROCESSES", num_workers))
    pid = int(os.environ.get("DTX_PROCESS_ID", 0))
    if nproc <= 1:
        return {"initialized": False, "process_id": 0, "num_processes": 1}
    # Liveness knobs (seconds). The jax defaults (heartbeat 100, shutdown
    # 300) assume dedicated hosts; the local multi-host simulator runs many
    # trainer processes on shared cores where one can legitimately stall
    # past 100 s under load — the coordinator then declares it dead and its
    # PEER fatally aborts after finishing all its work (observed: shutdown
    # barrier failure in the 4-concurrent-jobs e2e on a 1-core machine).
    # LocalProcessBackend raises these for simulated hosts; real pods keep
    # the defaults unless the operator overrides.
    heartbeat_s = int(os.environ.get("DTX_DIST_HEARTBEAT_S", "100"))
    shutdown_s = int(os.environ.get("DTX_DIST_SHUTDOWN_S", "300"))
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        heartbeat_timeout_seconds=heartbeat_s,
        shutdown_timeout_seconds=shutdown_s,
    )
    return {
        "initialized": True,
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
    }
