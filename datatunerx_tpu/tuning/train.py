"""Trainer entrypoint: ``python -m datatunerx_tpu.tuning.train --model_name_or_path … --train_path …``

The TPU-native replacement for the reference's Ray Train driver (reference
cmd/tuning/train.py): one identical program per TPU host — no Ray, no
per-worker init function; `jax.distributed` + GSPMD replace TorchTrainer/DDP
(SURVEY.md §7.1). Pipeline:

  parse → distributed init → load model+tokenizer → template/encode/pack →
  mesh → Trainer → (resume?) → epoch loop [train_step, log, eval, save] →
  final checkpoint + completion manifest (+ optional merged export)

Reference bug fixed here (SURVEY.md §7.5): eval loads evaluation_path, not
train_path (reference train.py:346-348 loads the train file twice).
"""

from __future__ import annotations

import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.data import BatchIterator, CsvDataset, get_template
from datatunerx_tpu.data.prefetch import (
    HostPrefetcher,
    MetricsBuffer,
    PipelineStats,
    prefetch_batches,
)
from datatunerx_tpu.data.preprocess import preprocess_preference_records
from datatunerx_tpu.parallel.distributed import maybe_initialize_distributed
from datatunerx_tpu.parallel.mesh import make_mesh, mesh_shape_for
from datatunerx_tpu.parallel.sharding import place_batch
from datatunerx_tpu.training import TrainConfig, Trainer
from datatunerx_tpu.training.checkpoint import (
    CheckpointManager,
    export_merged_model,
    write_manifest,
)
from datatunerx_tpu.training.metrics_log import MetricsLogger
from datatunerx_tpu.tuning.parser import TrainArgs, parse_train_args
from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer


def run(args: TrainArgs) -> dict:
    dist = maybe_initialize_distributed(args.num_workers)
    is_main = dist["process_id"] == 0

    # ----- model -------------------------------------------------------
    overrides = dict(
        remat=args.remat,
        attention_impl=args.attention,
    )
    if args.rope_scaling:
        overrides.update(
            rope_scaling_type=args.rope_scaling,
            rope_scaling_factor=args.rope_scaling_factor,
        )
    if args.quantization:
        if args.quantization == "int4" and args.quantization_type == "fp4":
            raise NotImplementedError(
                "fp4 is not supported; use nf4 (the reference default, "
                "cmd/tuning/parser.py:45-47)"
            )
        if args.finetuning_type != "lora":
            raise ValueError(
                "--quantization requires finetuning_type lora "
                "(quantized base weights are frozen, as with bitsandbytes+peft)"
            )
        overrides["quantization"] = args.quantization
        overrides["quant_impl"] = args.quant_impl
    dtype = jnp.bfloat16 if args.bf16 else np.float32
    cfg, params, tokenizer = load_model_and_tokenizer(
        args.model_name_or_path, dtype=dtype, seed=args.seed,
        config_overrides=overrides,
    )

    # export-only invocation: --export_dir with no --train_path
    if args.train_path is None:
        export_merged_model(jax.device_get(params), cfg, args.export_dir)
        return {"steps": 0, "metrics": {}, "manifest": None,
                "checkpoint_dir": None, "export_dir": args.export_dir}

    if args.quantization:
        from datatunerx_tpu.ops.quant import quantize_model_params

        params = quantize_model_params(params, args.quantization)

    # ----- data --------------------------------------------------------
    template = get_template(args.template, tokenizer)
    pad_id = tokenizer.pad_token_id or 0
    if args.streaming:
        train_ds = None  # records never materialize; see iterator below
        train_examples = None
    else:
        train_ds = CsvDataset(args.train_path, columns=args.columns_map)
    if args.streaming:
        pass  # encoded lazily by StreamingBatchIterator below
    elif args.stage in ("dpo", "rm"):
        train_examples = preprocess_preference_records(
            train_ds.records, template, tokenizer,
            cutoff_len=args.block_size, columns=args.columns_map,
        )
    elif args.stage == "ppo":
        from datatunerx_tpu.data.preprocess import preprocess_prompt_records

        train_examples = preprocess_prompt_records(
            train_ds.records, template, tokenizer,
            cutoff_len=args.block_size, columns=args.columns_map,
        )
    elif args.stage == "pt":
        from datatunerx_tpu.data.preprocess import preprocess_pretrain_records

        train_examples = preprocess_pretrain_records(
            train_ds.records, tokenizer,
            cutoff_len=args.block_size, columns=args.columns_map,
        )
    else:
        train_examples = train_ds.encode(template, tokenizer,
                                         cutoff_len=args.block_size)
    if not args.streaming and not train_examples:
        raise RuntimeError("Empty dataset!")
    eval_examples = None
    eval_records = None
    if args.evaluation_path and args.stage == "ppo" and is_main:
        print("[ppo] --evaluation_path ignored: PPO's held-out signal is the "
              "reward/KL curve, not a loss over a fixed eval set", flush=True)
    if args.evaluation_path and args.stage != "ppo":
        eval_ds = CsvDataset(args.evaluation_path, columns=args.columns_map)
        if args.stage in ("dpo", "rm"):
            # preference eval: mean pairwise loss over held-out pairs
            eval_examples = preprocess_preference_records(
                eval_ds.records, template, tokenizer,
                cutoff_len=args.block_size, columns=args.columns_map,
            )
        elif args.stage == "pt":
            from datatunerx_tpu.data.preprocess import (
                preprocess_pretrain_records,
            )

            eval_examples = preprocess_pretrain_records(
                eval_ds.records, tokenizer,
                cutoff_len=args.block_size, columns=args.columns_map,
            )
        else:
            eval_records = eval_ds.records
            eval_examples = eval_ds.encode(template, tokenizer,
                                           cutoff_len=args.block_size)

    # ----- mesh --------------------------------------------------------
    n_dev = len(jax.devices())
    dims = dict(args.mesh_dims or {})
    dcn_dp = int(dims.pop("dcn", 1) or 1)  # multi-slice: dp's major dim on DCN
    shape = mesh_shape_for(
        n_dev,
        dp=dims.get("dp"),
        fsdp=dims.get("fsdp", 1 if "dp" in dims else None),
        tp=dims.get("tp", 1),
        sp=dims.get("sp", 1),
    )
    mesh = make_mesh(shape, dcn_dp=dcn_dp)
    data_par = shape[0] * shape[1]

    grad_accum = args.gradient_accumulation_steps
    if args.stage == "ppo":
        if grad_accum > 1 and is_main:
            print(f"[ppo] --gradient_accumulation_steps {grad_accum} ignored:"
                  " a PPO step already makes ppo_epochs optimization passes "
                  "per rollout batch", flush=True)
        grad_accum = 1
    global_batch = args.per_device_train_batch_size * data_par * grad_accum
    iterator_cls = BatchIterator
    if args.stage in ("dpo", "rm"):
        from datatunerx_tpu.data.loader import PreferenceBatchIterator

        iterator_cls = PreferenceBatchIterator
    elif args.stage == "ppo":
        from datatunerx_tpu.data.loader import PromptBatchIterator

        iterator_cls = PromptBatchIterator
    if args.streaming:
        from datatunerx_tpu.data.loader import (
            StreamingBatchIterator,
            StreamingCsvDataset,
        )

        it = StreamingBatchIterator(
            StreamingCsvDataset(args.train_path, columns=args.columns_map),
            template, tokenizer,
            global_batch=global_batch,
            block_size=args.block_size,
            pad_id=pad_id,
            grad_accum=grad_accum,
            buffer_size=args.shuffle_buffer,
            seed=args.seed,
            host_id=dist["process_id"],
            num_hosts=dist["num_processes"],
            stage=args.stage,
        )
        # epoch length is unknown for a stream; the loop below re-opens the
        # stream (new shuffle order) until max_steps (validated > 0) land
        total_steps = args.max_steps
        steps_per_epoch = total_steps
    else:
        it = iterator_cls(
            train_examples,
            global_batch=global_batch,
            block_size=args.block_size,
            pad_id=pad_id,
            grad_accum=grad_accum,
            seed=args.seed,
            pack=args.pack_sequences,
            host_id=dist["process_id"],
            num_hosts=dist["num_processes"],
        )
        steps_per_epoch = it.steps_per_epoch()
        if steps_per_epoch == 0:
            raise RuntimeError(
                f"dataset ({len(train_examples)} examples) smaller than one "
                f"global batch ({global_batch})"
            )
        total_steps = (
            args.max_steps if args.max_steps > 0
            else int(math.ceil(steps_per_epoch * args.num_train_epochs))
        )

    # ----- trainer -----------------------------------------------------
    tcfg = TrainConfig(
        finetuning_type=args.finetuning_type,
        lora_rank=args.lora_rank,
        lora_alpha=args.lora_alpha,
        lora_dropout=args.lora_dropout,
        lora_targets=args.lora_targets,
        num_layer_trainable=args.num_layer_trainable,
        name_module_trainable=args.name_module_trainable,
        learning_rate=args.learning_rate,
        scheduler=args.lr_scheduler_type,
        optimizer=args.optim,
        warmup_ratio=args.warmup_ratio,
        weight_decay=args.weight_decay,
        max_grad_norm=args.max_grad_norm,
        # each PPO step runs ppo_epochs optimizer updates, and the optax
        # schedule counts UPDATES — scale the horizon so the LR decays over
        # the whole run instead of finishing ppo_epochs× early
        total_steps=(total_steps * max(args.ppo_epochs, 1)
                     if args.stage == "ppo" else total_steps),
        grad_accum=grad_accum,
        neftune_alpha=args.neft_alpha,
        compute_dtype=jnp.bfloat16 if args.bf16 else None,
        stage=args.stage if args.stage in ("dpo", "rm", "ppo") else "sft",
        dpo_beta=args.dpo_beta,
    )
    if args.stage == "ppo":
        from datatunerx_tpu.training.ppo import (
            PPOConfig,
            PPOTrainer,
            load_reward_model,
        )

        reward_lora, reward_scaling = load_reward_model(
            cfg, params, args.reward_model, mesh=mesh)
        trainer = PPOTrainer(
            cfg, tcfg,
            PPOConfig(
                gen_len=args.ppo_gen_len,
                temperature=args.ppo_temperature,
                kl_coef=args.init_kl_coef,
                ppo_target=args.ppo_target,
                ppo_epochs=args.ppo_epochs,
                score_norm=args.ppo_score_norm,
            ),
            reward_lora=reward_lora,
            reward_scaling=reward_scaling,
            eos_id=tokenizer.eos_token_id,
            pad_id=pad_id,
            mesh=mesh,
        )
    else:
        trainer = Trainer(cfg, tcfg, mesh=mesh)
    state = trainer.init_state(params, jax.random.PRNGKey(args.seed))

    from datatunerx_tpu.utils import storage

    run_name = args.uid or os.path.basename(args.output_dir.rstrip("/")) or "run"
    ckpt_dir = storage.join(args.storage_path, run_name, "checkpoints")
    ckpt = CheckpointManager(ckpt_dir, save_interval_steps=args.save_steps)
    start_step = 0
    if args.resume and ckpt.latest_step() is not None:
        restored, start_step = ckpt.restore(state)
        if restored is not None:
            state = trainer.place_state(restored)
            if args.stage == "ppo":
                from datatunerx_tpu.training.ppo import load_controller_state

                cs = load_controller_state(ckpt_dir)
                if cs is not None:
                    trainer.kl_coef = float(cs["kl_coef"])
            if is_main:
                print(f"[resume] restored step {start_step} from {ckpt_dir}", flush=True)

    logger = MetricsLogger(
        args.output_dir, total_steps,
        metrics_export_address=args.metrics_export_address, uid=args.uid,
        # lets the once-per-run prefetch advisory suggest a concrete
        # deeper --prefetch_depth when pipe_step_wait_ms p95 says the
        # step loop is starved by the input path
        prefetch_depth=args.prefetch_depth,
    )

    # ----- loop --------------------------------------------------------
    # profiling (SURVEY.md §5.1 — the reference exposes only the Ray
    # dashboard): capture a profiler trace for steps [2, 2+N) viewable in
    # TensorBoard/XProf; the trace dir lands in the completion manifest
    trace_dir = os.path.join(args.output_dir, "trace")
    profiling = {"active": False, "done": args.profile_steps <= 0}

    step_fn = trainer.step if args.stage == "ppo" else trainer.train_step
    # pipelined input path (data/prefetch.py): host batch build in a
    # background thread, batch N+1 placed on the mesh while step N executes.
    # PPO keeps its synchronous path — its step interleaves rollout
    # generation with optimization and places prompt batches itself.
    # Streaming + in-training generative eval: the stream tokenizes inside
    # the prefetch worker while the eval encodes on the main thread, and HF
    # fast tokenizers are not thread-safe ("Already borrowed" RuntimeError
    # would kill the run mid-epoch) — so the iterator clones the tokenizer
    # per encoding thread (loader.py ensure_thread_safe_encoding) and the
    # pipeline stays on; only a non-clonable tokenizer forces the old
    # synchronous fallback. Non-streaming pipelines never tokenize in the
    # worker (examples are pre-encoded; the worker only pads/packs).
    gen_eval_in_training = (args.predict_with_generate
                            and args.generate_eval_steps > 0)
    stream_thread_safe = True
    if args.prefetch_depth > 0 and args.streaming and gen_eval_in_training:
        stream_thread_safe = it.ensure_thread_safe_encoding()
    pipelined = (args.prefetch_depth > 0 and args.stage != "ppo"
                 and not (args.streaming and gen_eval_in_training
                          and not stream_thread_safe))
    if (args.prefetch_depth > 0 and args.streaming and gen_eval_in_training
            and not stream_thread_safe and is_main):
        print("[pipeline] disabled: --streaming with in-training generative "
              "eval shares one NON-CLONABLE tokenizer across threads",
              flush=True)
    pipe_stats = PipelineStats() if pipelined else None
    accum_batches = grad_accum > 1
    # non-blocking logging: step outputs buffer on device and resolve one
    # logging interval behind (or as soon as they report ready), so a logging
    # boundary never drains the dispatch pipeline
    mbuf = MetricsBuffer(lag=1)

    def _log_resolved(resolved):
        nonlocal final_metrics
        for s_done, host in resolved:
            logger.log_train(s_done, host)
            final_metrics = host

    step = 0  # counts up through start_step (skipping those batches) on resume
    final_metrics: dict = {}
    if args.streaming:
        import itertools

        epochs = itertools.count()  # re-open the stream until max_steps land
    else:
        epochs = range(int(math.ceil(total_steps / steps_per_epoch)))
    done = False
    try:
      for epoch in epochs:
        if done:
            break
        saw_batch = False
        src = it.epoch(epoch)
        # resumed: fast-forward the data stream on HOST batches, before the
        # pipeline spins up, so skipped batches are never placed on device
        # (and never past total_steps — an already-complete run must exit in
        # O(1), not re-tokenize every skipped batch)
        exhausted = False
        while step < start_step and step < total_steps:
            try:
                next(src)
            except StopIteration:
                exhausted = True
                break
            saw_batch = True
            step += 1
        if step >= total_steps:
            done = True
            break
        host_pf: HostPrefetcher | None = None
        if exhausted:
            batches = iter(())
        elif pipelined:
            batches, host_pf = prefetch_batches(
                src,
                place_fn=lambda b: place_batch(b, mesh, accum=accum_batches),
                # a retuned depth survives epoch boundaries: the advisory's
                # live resize carries into every later epoch's prefetcher
                depth=logger.effective_prefetch_depth()
                or args.prefetch_depth,
                stats=pipe_stats,
            )
            # hand the LIVE prefetcher to the advisory so it retunes the
            # bounded queue in-run instead of only printing a flag
            logger.attach_prefetcher(host_pf)
        else:
            batches = src
        try:
            # dtxlint: hot-begin -- the step loop: one iteration per train
            # step, so any host sync here stalls the dispatch pipeline
            for batch in batches:
                saw_batch = True
                if step >= total_steps:
                    done = True
                    break
                if not profiling["done"] and not profiling["active"] and step >= start_step + 1:
                    jax.profiler.start_trace(trace_dir)
                    profiling["active"] = True
                    profiling["until"] = step + args.profile_steps
                state, metrics = step_fn(state, batch)
                step += 1
                if profiling["active"] and step >= profiling["until"]:
                    # one-shot sync when the profiler window closes, so the
                    # trace contains finished steps; not a per-step stall
                    jax.block_until_ready(metrics["loss"])  # dtxlint: disable=DTX001
                    jax.profiler.stop_trace()
                    profiling.update(active=False, done=True)
                    if is_main:
                        print(f"[profile] trace captured to {trace_dir}", flush=True)
                if is_main and (step % args.logging_steps == 0 or step == total_steps):
                    extra = {"epoch": round(step / steps_per_epoch, 3)}
                    if pipe_stats is not None:
                        extra.update(pipe_stats.snapshot())
                    mbuf.push(step, metrics, extra)
                    _log_resolved(mbuf.pop_ready())
                if args.save_steps > 0:
                    if ckpt.maybe_save(state, step) and args.stage == "ppo" \
                            and is_main:
                        from datatunerx_tpu.training.ppo import (
                            save_controller_state,
                        )

                        save_controller_state(ckpt_dir, step, trainer.kl_coef)
                if eval_examples and args.eval_steps > 0 and step % args.eval_steps == 0:
                    _run_eval(trainer, state, eval_examples, args, pad_id, logger,
                              step, is_main, dist)
                # dtxlint: hot-end -- the periodic generative eval below is
                # host-driven autoregressive decode by design (small sample,
                # main process only); its syncs are inherent, not stalls
                if (args.predict_with_generate and eval_records
                        and args.generate_eval_steps > 0
                        and step % args.generate_eval_steps == 0
                        and step < total_steps  # final step gets the full pass below
                        and dist["num_processes"] == 1 and is_main):
                    # in-training generative eval: a small sample at step
                    # intervals so rouge/bleu CURVES exist, not just a final
                    # point (reference only evaluates at the end)
                    _generative_eval_step(trainer, state, cfg, tokenizer, template,
                                          eval_records, args, logger, step,
                                          tcfg.finetuning_type)
        finally:
            if host_pf is not None:
                # stops the worker thread even when the loop exits early
                # (done, max_steps, an exception) mid-epoch
                host_pf.close()
        if (eval_examples and args.eval_steps == 0 and not done
                and step < total_steps):
            # eval_steps=0 → once per epoch (final epoch's eval happens below)
            _run_eval(trainer, state, eval_examples, args, pad_id, logger,
                      step, is_main, dist)
        if not saw_batch:  # streaming: a pass produced no full batch
            if step == 0:
                raise RuntimeError("Empty dataset!")
            break

    finally:
        # also on a crash/interrupt mid-run: resolve buffered records rather
        # than dropping up to a logging interval of already-computed metrics
        _log_resolved(mbuf.drain())
    if profiling["active"]:  # window extended past the last step
        jax.profiler.stop_trace()
        profiling.update(active=False, done=True)

    # ----- final eval / save / manifest --------------------------------
    if eval_examples:
        final_metrics.update(
            _run_eval(trainer, state, eval_examples, args, pad_id, logger,
                      step, is_main, dist)
        )
    ckpt.maybe_save(state, step, force=True)
    if args.stage == "ppo" and is_main:
        from datatunerx_tpu.training.ppo import save_controller_state

        save_controller_state(ckpt_dir, step, trainer.kl_coef)

    if args.predict_with_generate and eval_records:
        # single-host only: generation is a process-0-only loop, which would
        # touch non-addressable shards / desync collectives under multi-host
        if dist["num_processes"] > 1:
            if is_main:
                print("[generate] skipped: predict_with_generate is "
                      "single-host only for now", flush=True)
        else:
            from datatunerx_tpu.training.generate import generative_eval

            gen_lora = None
            if tcfg.finetuning_type == "lora":
                gen_lora = (state.lora, trainer.scaling)
            try:
                gen_metrics = generative_eval(
                    state.params, cfg, tokenizer, template, eval_records,
                    args.output_dir,
                    lora=gen_lora,
                    max_new_tokens=args.max_new_tokens,
                    max_examples=args.generate_examples,
                    columns=args.columns_map,
                )
            except Exception as e:  # noqa: BLE001 — never lose a finished run
                print(f"[generate] failed (run preserved): {e}", flush=True)
                gen_metrics = {}
            if gen_metrics:
                logger.log_eval(step, gen_metrics)
                final_metrics.update(gen_metrics)

    manifest_path = None
    if is_main:
        checkpoint_uri = storage.join(ckpt_dir, str(step))
        manifest_path = write_manifest(
            args.storage_path, run_name, checkpoint_uri,
            metrics=final_metrics,
            extra={
                "model": args.model_name_or_path,
                "finetuning_type": args.finetuning_type,
                # serving merges the adapter with THIS scaling (alpha/rank);
                # without it a non-default --lora_alpha run would be merged
                # at the wrong scale at serve time
                "lora_scaling": (
                    trainer.scaling if tcfg.finetuning_type == "lora" else None
                ),
                "lora_alpha": (
                    args.lora_alpha if tcfg.finetuning_type == "lora" else None
                ),
                "lora_rank": (
                    args.lora_rank if tcfg.finetuning_type == "lora" else None
                ),
                "lora_targets": (
                    list(args.lora_targets)
                    if tcfg.finetuning_type == "lora" else None
                ),
                # stage/optimizer let downstream consumers (e.g. --stage ppo
                # loading this run as its reward model) rebuild a matching
                # restore template without guessing
                "stage": args.stage,
                "optimizer": args.optim,
                "reward_model": args.reward_model,
                "template": args.template,
                "mesh": dict(zip(("dp", "fsdp", "tp", "sp"), shape)),
                "steps": step,
                "trace": trace_dir if (args.profile_steps > 0 and profiling["done"]) else None,
            },
        )
        if args.export_dir:
            lora = state.lora if tcfg.finetuning_type == "lora" else None
            export_params = jax.device_get(state.params)
            if args.quantization:
                from datatunerx_tpu.models.lora import target_dims
                from datatunerx_tpu.ops.quant import dequantize_model_params

                export_params = dequantize_model_params(
                    export_params, args.quantization,
                    dims_fn=lambda n: target_dims(cfg, n),
                )
            export_merged_model(
                export_params, cfg, args.export_dir,
                lora=jax.device_get(lora) if lora is not None else None,
                scaling=trainer.scaling,
            )
    ckpt.close()
    return {
        "steps": step,
        "metrics": final_metrics,
        "manifest": manifest_path,
        "checkpoint_dir": ckpt_dir,
    }


def _generative_eval_step(trainer, state, cfg, tokenizer, template,
                          eval_records, args, logger, step, finetuning_type):
    from datatunerx_tpu.training.generate import generative_eval

    gen_lora = (state.lora, trainer.scaling) if finetuning_type == "lora" else None
    try:
        m = generative_eval(
            state.params, cfg, tokenizer, template, eval_records,
            args.output_dir, lora=gen_lora,
            max_new_tokens=args.max_new_tokens,
            # keep interval evals cheap: a handful of examples per point
            max_examples=min(args.generate_examples, 8),
            columns=args.columns_map,
        )
    except Exception as e:  # noqa: BLE001 — never kill training for an eval
        print(f"[generate@{step}] failed (training continues): {e}", flush=True)
        return
    if m:
        logger.log_eval(step, m)


def _run_eval(trainer, state, eval_examples, args, pad_id, logger, step,
              is_main, dist):
    data_par = 1
    if trainer.mesh is not None:
        data_par = trainer.mesh.shape["dp"] * trainer.mesh.shape["fsdp"]
    iterator_cls = BatchIterator
    if args.stage in ("dpo", "rm"):
        from datatunerx_tpu.data.loader import PreferenceBatchIterator

        iterator_cls = PreferenceBatchIterator
    eval_it = iterator_cls(
        eval_examples,
        global_batch=args.per_device_eval_batch_size * data_par,
        block_size=args.block_size,
        pad_id=pad_id,
        shuffle=False,
        drop_remainder=False,  # pad the tail: every eval example counts
        host_id=dist["process_id"],
        num_hosts=dist["num_processes"],
    )
    if args.prefetch_depth > 0 and trainer.mesh is not None:
        # eval rides the same pipeline as training (ROADMAP follow-on):
        # batch N+1 builds on the host and lands on the mesh while eval_step
        # N runs — eval_step already accepts PlacedBatch, and eval examples
        # are pre-encoded so the worker never touches the tokenizer
        batches, host_pf = prefetch_batches(
            eval_it.epoch(0),
            place_fn=lambda b: place_batch(b, trainer.mesh),
            depth=args.prefetch_depth,
        )
        try:
            m = trainer.evaluate(state, batches)
        finally:
            host_pf.close()
    else:
        m = trainer.evaluate(state, ({k: jnp.asarray(v) for k, v in b.items()}
                                     for b in eval_it.epoch(0)))
    if args.stage in ("dpo", "rm"):
        # eval_loss IS the mean pairwise loss over held-out pairs; exp(loss)
        # is not a perplexity in these stages
        m.pop("perplexity", None)
    if is_main:
        logger.log_eval(step, m)
    return m


def main(argv=None):
    args = parse_train_args(argv)
    result = run(args)
    print(f"[done] {result['steps']} steps; manifest: {result['manifest']}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
