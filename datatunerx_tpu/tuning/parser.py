"""Trainer CLI: the Go↔Python contract surface.

Mirrors the flag set the controller emits (reference
internal/controller/finetune/finetune_controller.go:457-514) plus the trainer's
own schema (reference cmd/tuning/parser.py — ModelArguments /
FinetuningArguments / DataArguments / training args), with TPU additions
(--mesh, --attention, --template, --save_steps).

Contract-compat notes (reference bugs we tolerate, SURVEY.md §7.5):
- the controller sends ``--lora_r`` but the reference parser only defines
  ``--lora_rank`` — we accept both;
- ``--per_device_train_batch_size `` is emitted with a trailing space in the
  flag name — shell splitting makes that harmless, no action needed;
- ``--deepspeed`` is accepted and ignored (sharding comes from --mesh);
- ``--columns`` may arrive Go-strconv.Quote()d — we unquote.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Optional


@dataclasses.dataclass
class TrainArgs:
    # model (reference cmd/tuning/parser.py:12-109)
    model_name_or_path: str
    quantization: Optional[str] = None  # int4 | int8
    quantization_type: str = "nf4"  # fp4 | nf4
    double_quantization: bool = True
    quant_impl: str = "pallas"  # pallas (fused kernels) | xla (dequant+dot);
    # TPU addition — replaces bitsandbytes' kernel selection (reference
    # train.py:224-234 always uses bnb CUDA kernels when quantized)
    rope_scaling: Optional[str] = None  # linear | dynamic
    rope_scaling_factor: float = 2.0
    flash_attn: bool = False
    shift_attn: bool = False
    checkpoint_dir: Optional[str] = None  # resume/merge adapters
    export_dir: Optional[str] = None
    # finetuning (reference cmd/tuning/parser.py:112-221)
    stage: str = "sft"  # pt | sft | dpo | rm | ppo
    finetuning_type: str = "lora"  # lora | freeze | full | none
    num_layer_trainable: int = 3
    name_module_trainable: str = "mlp"
    lora_rank: int = 8
    lora_alpha: float = 32.0
    lora_dropout: float = 0.1
    lora_target: str = "q_proj,v_proj"
    neft_alpha: float = 0.0
    dpo_beta: float = 0.1  # reference reserves dpo knobs (parser.py:170-185)
    # ppo (reference reserves --stage ppo + knobs, parser.py:117-120,170-185,
    # and a --reward_model arg :74-76; runtime is new capability,
    # training/ppo.py)
    reward_model: Optional[str] = None  # --stage rm run dir (storage/<uid>)
    ppo_epochs: int = 2
    ppo_target: float = 6.0  # >0: adaptive KL controller target (reference
    # parser.py default 6.0 — adaptive KL is ON by default; pass 0 to disable)
    ppo_score_norm: bool = False
    init_kl_coef: float = 0.1
    ppo_gen_len: int = 64
    ppo_temperature: float = 1.0
    num_workers: int = 1
    storage_path: Optional[str] = None
    metrics_export_address: Optional[str] = None
    uid: Optional[str] = None
    # data (reference cmd/tuning/parser.py:224-247)
    train_path: Optional[str] = None
    evaluation_path: Optional[str] = None
    columns: Optional[str] = None
    block_size: int = 1024
    template: str = "llama2"  # reference hardcodes llama2 (train.py:63)
    pack_sequences: bool = False
    streaming: bool = False  # shuffle-buffered streaming ingest (sft/pt)
    shuffle_buffer: int = 2048
    # training loop (HF Seq2SeqTrainingArguments subset the pipeline uses)
    output_dir: str = "result"
    per_device_train_batch_size: int = 4
    per_device_eval_batch_size: int = 4
    gradient_accumulation_steps: int = 1
    learning_rate: float = 2e-4
    num_train_epochs: float = 1.0
    max_steps: int = -1
    lr_scheduler_type: str = "cosine"
    optim: str = "adamw"
    warmup_ratio: float = 0.0
    weight_decay: float = 0.0
    max_grad_norm: float = 1.0
    logging_steps: int = 10
    save_steps: int = 0  # 0 = final only (reference behavior)
    eval_steps: int = 0  # 0 = once per epoch when eval set present
    seed: int = 42
    fp16: bool = False  # accepted for contract; bf16 is the TPU dtype
    bf16: bool = True
    # generative eval (reference GenEvalSeq2SeqTrainer, cmd/tuning/trainer.py)
    predict_with_generate: bool = False
    max_new_tokens: int = 64
    generate_examples: int = 32
    generate_eval_steps: int = 0  # 0 = end-of-run only; N = also every N steps
    # TPU additions
    prefetch_depth: int = 2  # batches in flight in the pipelined input path
    # (host prefetch + double-buffered device placement, data/prefetch.py);
    # 0 = synchronous feeding (the pre-pipeline loop). PPO always synchronous.
    profile_steps: int = 0  # capture a jax.profiler trace for N steps
    mesh: Optional[str] = None  # e.g. "dp=4,fsdp=2,tp=1,sp=1"
    attention: str = "xla"  # xla | flash | ring
    remat: str = "dots"  # none | dots | full
    deepspeed: Optional[str] = None  # accepted, ignored
    resume: bool = True  # auto-resume from latest checkpoint

    def __post_init__(self):
        if self.stage not in ("pt", "sft", "rm", "ppo", "dpo"):
            raise ValueError(f"invalid --stage {self.stage}")
        if self.stage in ("dpo", "rm", "ppo") and self.finetuning_type != "lora":
            raise ValueError(
                f"--stage {self.stage} requires --finetuning_type lora")
        if self.stage == "ppo" and self.train_path is not None \
                and not self.reward_model:
            raise ValueError(
                "--stage ppo requires --reward_model (an --stage rm run "
                "directory: <storage_path>/<uid>)")
        if self.streaming:
            if self.stage not in ("sft", "pt"):
                raise ValueError("--streaming supports stages sft/pt only")
            if self.max_steps <= 0:
                raise ValueError(
                    "--streaming needs --max_steps (epoch length is unknown "
                    "without materializing the stream)")
            if self.pack_sequences:
                raise ValueError(
                    "--streaming and --pack_sequences are exclusive (packing "
                    "needs the whole dataset to fill blocks densely)")
        if self.prefetch_depth < 0:
            raise ValueError("--prefetch_depth must be >= 0 (0 disables the "
                             "pipelined input path)")
        if self.finetuning_type not in ("lora", "freeze", "full", "none"):
            raise ValueError(f"invalid --finetuning_type {self.finetuning_type}")
        if self.quantization not in (None, "int4", "int8"):
            raise ValueError("We only accept int4 or int8 quantization.")
        if self.quant_impl not in ("xla", "pallas"):
            raise ValueError("quant_impl must be 'pallas' or 'xla'")
        if self.rope_scaling not in (None, "linear", "dynamic"):
            raise ValueError(f"invalid --rope_scaling {self.rope_scaling}")
        if self.train_path is None and self.export_dir is None:
            raise ValueError("--train_path must be specified")
        if self.storage_path is None:
            raise ValueError("--storage_path must be specified")

    @property
    def lora_targets(self) -> tuple:
        return tuple(t.strip() for t in self.lora_target.split(",") if t.strip())

    @property
    def columns_map(self) -> Optional[dict]:
        if not self.columns:
            return None
        text = self.columns
        if text.startswith('"') and text.endswith('"'):  # Go strconv.Quote
            text = json.loads(text)
        return json.loads(text)

    @property
    def mesh_dims(self) -> Optional[dict]:
        if not self.mesh:
            return None
        dims = {}
        for part in self.mesh.split(","):
            k, _, v = part.partition("=")
            dims[k.strip()] = int(v)
        return dims


_BOOLS = {"fp16", "bf16", "flash_attn", "shift_attn", "double_quantization",
          "pack_sequences", "resume", "predict_with_generate",
          "ppo_score_norm", "streaming"}
_ALIASES = {"lora_r": "lora_rank"}  # controller emits --lora_r


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="datatunerx-tpu-train", allow_abbrev=False)
    for f in dataclasses.fields(TrainArgs):
        name = "--" + f.name
        if f.name in _BOOLS:
            # accept "--flag true/false" (Go emits values) and bare "--flag"
            p.add_argument(name, nargs="?", const="true",
                           default=None if f.default is None else str(f.default))
        elif f.default is dataclasses.MISSING:
            p.add_argument(name, required=True)
        else:
            p.add_argument(name, default=f.default)
    for alias, target in _ALIASES.items():
        p.add_argument("--" + alias, dest=target, default=argparse.SUPPRESS)
    return p


def parse_train_args(argv=None) -> TrainArgs:
    ns = vars(build_argparser().parse_args(argv))
    kwargs = {}
    for f in dataclasses.fields(TrainArgs):
        if f.name not in ns or ns[f.name] is None:
            continue
        raw = ns[f.name]
        if raw == "" and f.default is None:
            continue  # empty string clears an optional flag (controller may
            # emit e.g. --metrics_export_address "" / --quantization "")
        if f.name in _BOOLS:
            kwargs[f.name] = str(raw).lower() in ("true", "1", "yes")
        elif f.type in ("int", int):
            kwargs[f.name] = int(raw)
        elif f.type in ("float", float):
            kwargs[f.name] = float(raw)
        else:
            kwargs[f.name] = raw
    return TrainArgs(**kwargs)
